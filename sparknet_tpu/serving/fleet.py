"""Fleet serving router: N OS inference-worker processes behind one
InferenceServer-shaped front end.

Why a fleet: every in-process replica shares one GIL, so PR 8 measured
8 replicas at only 1.20x single-replica QPS — the parallelism the
replica scheduler exposes is real on a TPU mesh but fake on host
threads.  SparkNet's own architecture is full-model replicas in
separate executor processes behind one driver (reference:
SparkNetArchitecture.scala — arXiv:1511.06051 §2), and this module is
that shape for serving: each worker process (fleet_worker.py) runs a
COMPLETE InferenceServer on its own device slice (or mesh slice via
shards=N), and the router speaks the existing serving interface —
`ReplicaScheduler` routes, `ModelStats` counts, `CircuitBreaker`s guard
— where "replica" now means "worker process".

Transport is elastic/ipc.py (the PR 12 proc substrate): spawn with a
CPU-pinned env + start_new_session, one-ready-line handshake with a
stderr tail on failure, then length-prefixed binary frames both ways
(atomic framing: one write per frame, writers serialized per pipe).  A
reader thread per worker routes reply frames to waiting dispatches by
`seq`; every wait is bounded (R006 discipline — IPC deadline, spawn
timeout, reap ladder).

Process-grained resilience, mirroring serving/resilience.py exactly:

- a dead (SIGKILL, crash), wedged (SIGSTOP — caught by the file-mtime
  heartbeat watchdog), or erroring worker trips its breaker: the slot
  is disabled (never the last enabled one), its queued items drain and
  requeue onto healthy workers (exactly-once: requeue bypasses
  queue_depth), in-flight dispatches fail fast when the reader sees
  EOF, and bounded per-request retries redispatch elsewhere;
- the maintenance thread respawns a FRESH process after the cooldown,
  waits for its warmed ready line, then earns re-admission through
  half-open probes (real end-to-end requests through the new process,
  drawing from the same fault schedule as live traffic);
- the optional autoscaler (ScalePolicy — the tick-indexed policy the
  in-process lane uses) parks/unparks whole worker processes;
- reload() hot-swaps generations fleet-wide with a dispatch barrier:
  the gate closes, in-flight batches finish, every live worker reloads,
  the fleet generation bumps, the gate reopens — so no response can
  ever carry a mixed generation and the generation sequence any client
  observes is monotone.

Faults for drills come from the SAME seeded ServeFaultPlan grammar as
PR 15 (errstorm/spike/kill), but `kill` here is a REAL SIGKILL to a
live worker pid.

Events are JSONL (DISTACC.md schema): worker_spawn / worker_ready /
worker_open / worker_respawn / worker_probe / worker_kill_injected /
fleet_reload / scale_up / scale_down / scale_suppressed / fleet_error.

Knobs (analysis/knobs.py + README table, R004):
SPARKNET_SERVE_FLEET_WORKERS (default worker count, 2),
SPARKNET_SERVE_FLEET_IPC_DEADLINE_S (per-frame round-trip bound, 30),
SPARKNET_SERVE_FLEET_HEARTBEAT_S (worker heartbeat period, 0.25),
SPARKNET_SERVE_FLEET_SPAWN_TIMEOUT_S (spawn->ready bound, 120); the
breaker window/error-threshold/cooldown/probe knobs are shared with
the in-process plane (serving/resilience.py declares them).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import queue
import shutil
import signal
import tempfile
import threading
import time  # sleep only; timestamps flow through obs.trace.now_s
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..elastic import ipc
from ..obs.trace import now_s, span
from .autoscale import AutoscaleConfig, ScalePolicy, SensorSample
from .errors import (DeadlineExceeded, ServerClosed, ServerOverloaded,
                     ServingError)
from .resilience import (BREAKER_COOLDOWN_ENV, BREAKER_ERRS_ENV,
                         BREAKER_WINDOW_ENV, PRIORITIES, PROBES_ENV,
                         CircuitBreaker, ServeFaultPlan, _env_float,
                         _env_int)
from .scheduler import ReplicaScheduler, SchedulerClosed, SchedulerFull
from .server import Response, _Request
from .stats import ModelStats

__all__ = ["FleetConfig", "FleetServer", "FleetModel",
           "FLEET_WORKERS_ENV", "FLEET_IPC_DEADLINE_ENV",
           "FLEET_HEARTBEAT_ENV", "FLEET_SPAWN_TIMEOUT_ENV"]

FLEET_WORKERS_ENV = "SPARKNET_SERVE_FLEET_WORKERS"
FLEET_IPC_DEADLINE_ENV = "SPARKNET_SERVE_FLEET_IPC_DEADLINE_S"
FLEET_HEARTBEAT_ENV = "SPARKNET_SERVE_FLEET_HEARTBEAT_S"
FLEET_SPAWN_TIMEOUT_ENV = "SPARKNET_SERVE_FLEET_SPAWN_TIMEOUT_S"

_WORKER_MODULE = "sparknet_tpu.serving.fleet_worker"


# ------------------------------------------------------------------- config
@dataclasses.dataclass
class FleetConfig:
    """Router knobs.  Batching fields mirror ServerConfig (the router's
    scheduler batches exactly like a lane's); fleet fields default from
    their env knobs so deployments tune without code."""

    workers: int = dataclasses.field(
        default_factory=lambda: _env_int(FLEET_WORKERS_ENV, 2))
    max_batch: int = 8
    max_wait_ms: float = 0.0
    queue_depth: int = 64
    min_fill: int = 1
    default_deadline_ms: Optional[float] = None
    ipc_deadline_s: float = dataclasses.field(
        default_factory=lambda: _env_float(FLEET_IPC_DEADLINE_ENV, 30.0))
    heartbeat_s: float = dataclasses.field(
        default_factory=lambda: _env_float(FLEET_HEARTBEAT_ENV, 0.25))
    spawn_timeout_s: float = dataclasses.field(
        default_factory=lambda: _env_float(FLEET_SPAWN_TIMEOUT_ENV,
                                           120.0))
    # breaker knobs are shared with the in-process resilience plane
    breaker_window: int = dataclasses.field(
        default_factory=lambda: _env_int(BREAKER_WINDOW_ENV, 16))
    breaker_error_threshold: float = dataclasses.field(
        default_factory=lambda: _env_float(BREAKER_ERRS_ENV, 0.5))
    breaker_min_samples: int = 4
    cooldown_s: float = dataclasses.field(
        default_factory=lambda: _env_float(BREAKER_COOLDOWN_ENV, 0.25))
    half_open_probes: int = dataclasses.field(
        default_factory=lambda: _env_int(PROBES_ENV, 3))
    max_retries: int = 2
    tick_s: float = 0.05            # maintenance thread period
    result_timeout_s: float = 120.0   # worker-side future bound
    autoscale: Optional[AutoscaleConfig] = None
    fault_plan: Optional[ServeFaultPlan] = None
    event_log: Optional[str] = None   # JSONL path (DISTACC.md schema)
    workdir: Optional[str] = None     # default: mkdtemp, removed on close
    force_cpu: bool = True

    def __post_init__(self) -> None:
        if self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if not 1 <= self.min_fill <= self.max_batch:
            raise ValueError(
                f"min_fill must be in [1, max_batch={self.max_batch}], "
                f"got {self.min_fill}")
        if self.ipc_deadline_s <= 0:
            raise ValueError(f"ipc_deadline_s must be > 0, "
                             f"got {self.ipc_deadline_s}")
        if self.heartbeat_s <= 0:
            raise ValueError(f"heartbeat_s must be > 0, "
                             f"got {self.heartbeat_s}")
        if self.spawn_timeout_s <= 0:
            raise ValueError(f"spawn_timeout_s must be > 0, "
                             f"got {self.spawn_timeout_s}")
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {self.max_retries}")
        if self.tick_s <= 0:
            raise ValueError(f"tick_s must be > 0, got {self.tick_s}")

    @property
    def hb_miss_after_s(self) -> float:
        """Stall threshold: 4 missed beats, floored at 1 s so a slow
        filesystem can't fake a wedge (proc.py's constant)."""
        return max(4.0 * self.heartbeat_s, 1.0)


@dataclasses.dataclass
class FleetModel:
    """Client-side description of the fleet's one model — what load()
    returns in place of a LoadedModel (the params live in the worker
    processes; this is the routing-relevant surface)."""

    name: str
    sample_shape: Tuple[int, ...]
    buckets: Tuple[int, ...]
    n_outputs: int
    quant: str
    shards: int
    _fleet: "FleetServer" = dataclasses.field(repr=False, default=None)

    @property
    def generation(self) -> int:
        return self._fleet.generation

    @property
    def n_replicas(self) -> int:
        return self._fleet.cfg.workers


class _Slot:
    """One worker slot: the process, its pipes, and the seq->queue
    reply routing its reader thread feeds.  Mutable fields are guarded
    by the router's `_mu` (state/proc/pid/incarnation/dispatch) or by
    `pending_mu` (the reply map)."""

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.state = "down"     # down|live|tripped|probing|parked
        self.proc = None
        self.pid: Optional[int] = None
        self.cfg_path = ""
        self.hb_path = ""
        self.stderr_path = ""
        self.stderr_f = None
        self.ready: Dict[str, Any] = {}
        self.incarnation = -1       # first spawn makes it 0
        self.dispatch = 0           # fault-plan index
        self.kill_fired = False     # plan kill latched (incarnation 0)
        self.write_lock = threading.Lock()
        self.pending_mu = threading.Lock()
        self.pending: Dict[int, "queue.Queue"] = {}
        self.reader: Optional[threading.Thread] = None


class FleetServer:
    """One-model serving front end over N worker processes.  Speaks the
    InferenceServer surface: load / submit / submit_many / reload /
    drain / close / stats, plus the control-plane observability hooks
    the chaos drill uses (all_closed, events_snapshot, fleet_snapshot,
    kill_worker)."""

    def __init__(self, config: Optional[FleetConfig] = None) -> None:
        self.cfg = config or FleetConfig()
        self._mu = threading.Lock()
        self._ev_mu = threading.Lock()     # serializes JSONL appends
        self._seq_mu = threading.Lock()
        self._seq = 0
        # serializes reload/respawn/scale.  A busy-flag lease (its own
        # condition, not a held mutex) because the critical sections
        # block for seconds — spawn waits, reap ladders, probe RPCs —
        # and holding a Lock across blocking work is the R008
        # anti-pattern this repo lints against.
        self._swap_cv = threading.Condition()
        self._swap_busy = False
        self._flight_cv = threading.Condition()
        self._inflight = 0
        self._swapping = False
        self._accepting = True
        self._closing = False
        self._closed = False
        self._started = False
        self._model: Optional[FleetModel] = None
        self._model_cfg: Dict[str, Any] = {}
        self._generation = 0
        self._sched: Optional[ReplicaScheduler] = None
        self._stats = ModelStats()
        self._slots: List[_Slot] = []
        self._breakers: List[CircuitBreaker] = []
        self._watchdog = ipc.MtimeWatchdog(self.cfg.hb_miss_after_s)
        self._policy: Optional[ScalePolicy] = (
            ScalePolicy(self.cfg.autoscale)
            if self.cfg.autoscale is not None else None)
        self._interactive_ewma_ms: Optional[float] = None
        self.events: List[dict] = []
        self._c: Dict[str, int] = {
            k: 0 for k in ("trips", "respawns", "requeued", "retried",
                           "probes_ok", "probes_failed", "hb_miss",
                           "proc_exits", "kills_injected", "restarts",
                           "scale_ups", "scale_downs")}
        self._own_workdir = self.cfg.workdir is None
        self.workdir = self.cfg.workdir
        self._stop_evt = threading.Event()
        self._maint: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    def load(self, name: str, spec: Optional[str] = None, *,
             weights: Optional[str] = None,
             buckets: Optional[Sequence[int]] = None,
             seed: int = 0, quant: Optional[str] = None,
             quant_min_agreement: Optional[float] = None,
             shards: Optional[int] = None) -> FleetModel:
        """Spawn the worker fleet (concurrent compiles, sequential
        ready-waits), verify every worker agrees on the model surface,
        and start routing.  One fleet serves ONE model — the worker
        processes each hold a full copy, so a second model belongs in a
        second fleet.  A worker that fails to load (bad spec, failed
        quant calibration floor) surfaces as a RuntimeError carrying
        its stderr tail."""
        if self._model is not None:
            raise ValueError(
                f"fleet already serves {self._model.name!r}; one fleet "
                f"serves one model (start another FleetServer)")
        if self._closing or self._closed:
            raise ServerClosed("fleet is shutting down")
        self._started = True
        with self._mu:    # pre-thread writes, but lint-uniform anyway
            if self.workdir is None:
                self.workdir = tempfile.mkdtemp(prefix="sparknet_fleet_")
            workdir = self.workdir
        os.makedirs(workdir, exist_ok=True)
        model_cfg = {
            "model": str(name), "spec": spec, "weights": weights,
            "buckets": list(buckets) if buckets is not None else None,
            "seed": int(seed), "quant": quant or "fp32",
            "quant_min_agreement": quant_min_agreement,
            "shards": shards, "max_batch": self.cfg.max_batch,
            "max_wait_ms": 0.0, "queue_depth": self.cfg.queue_depth,
            "heartbeat_s": self.cfg.heartbeat_s,
            "result_timeout_s": self.cfg.result_timeout_s,
            "force_cpu": self.cfg.force_cpu}
        slots = [_Slot(i) for i in range(self.cfg.workers)]
        breakers = [
            CircuitBreaker(window=self.cfg.breaker_window,
                           error_threshold=self.cfg.breaker_error_threshold,
                           min_samples=self.cfg.breaker_min_samples,
                           cooldown_s=self.cfg.cooldown_s,
                           half_open_probes=self.cfg.half_open_probes)
            for _ in range(self.cfg.workers)]
        with self._mu:
            self._model_cfg = model_cfg
            self._slots = slots
            self._breakers = breakers
        try:
            for slot in self._slots:      # concurrent compile fan-out
                self._spawn(slot)
            for slot in self._slots:
                self._finish_spawn(slot)
        except Exception:
            for slot in self._slots:
                self._kill_slot_proc(slot)
            raise
        r0 = self._slots[0].ready
        for slot in self._slots[1:]:
            for key in ("sample_shape", "buckets", "n_outputs", "quant",
                        "generation"):
                if slot.ready.get(key) != r0.get(key):
                    raise RuntimeError(
                        f"fleet worker {slot.idx} disagrees on {key}: "
                        f"{slot.ready.get(key)!r} != {r0.get(key)!r}")
        fm = FleetModel(
            name=str(name),
            sample_shape=tuple(int(d) for d in r0["sample_shape"]),
            buckets=tuple(int(b) for b in r0["buckets"]),
            n_outputs=int(r0["n_outputs"]),
            quant=str(r0.get("quant", "fp32")),
            shards=int(r0.get("shards", 1) or 1),
            _fleet=self)
        sched = ReplicaScheduler(
            self.cfg.workers, max_batch=self.cfg.max_batch,
            queue_depth=self.cfg.queue_depth,
            min_fill=self.cfg.min_fill,
            max_wait_ms=self.cfg.max_wait_ms,
            run=self._run_batch,
            name=f"fleet-{name}")
        with self._mu:
            self._model = fm
            self._sched = sched
        self._stats.observe_sensors(active_replicas=self.cfg.workers)
        self._maint = threading.Thread(
            target=self._loop, name=f"sparknet-fleet-{name}",
            daemon=True)
        self._maint.start()
        return self._model

    def __enter__(self) -> "FleetServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    @property
    def generation(self) -> int:
        with self._mu:
            return self._generation

    def drain(self) -> None:
        """Block until every admitted request has been delivered."""
        if self._sched is not None:
            self._sched.drain()

    def close(self, *, drain: bool = True) -> None:
        """Stop accepting; deliver (drain=True) or reject everything
        still queued; stop the maintenance thread, then the scheduler,
        then the workers (in that order — draining needs live workers,
        and no respawn may race the teardown).  Idempotent."""
        with self._mu:
            self._accepting = False
            if self._closed:
                return
            self._closed = True
            self._closing = True
        with self._flight_cv:       # unblock any swap-gated dispatch
            self._flight_cv.notify_all()
        self._stop_evt.set()
        if self._maint is not None and \
                self._maint is not threading.current_thread():
            self._maint.join(timeout=30.0)
        if self._sched is not None:
            for req in self._sched.stop(drain=drain):
                self._stats.bump("rejected_closed")
                req.future.set_exception(
                    ServerClosed("fleet closed before this request ran"))
        for slot in self._slots:
            self._stop_worker(slot)
        if self._own_workdir and self.workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)

    # ------------------------------------------------------------ admission
    def submit(self, model: str, sample, *,
               deadline_ms: Optional[float] = None,
               wait: bool = False,
               wait_timeout_s: Optional[float] = None,
               priority: str = "interactive") -> Future:
        """InferenceServer.submit, verbatim semantics: shape-checked
        admission, 503 on overload (or bounded backpressure with
        wait=True), immediate 504 for an unmeetable deadline; the
        future resolves to the same Response type, with `replica`
        carrying the worker index."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        fm = self._require_model(model)
        x = np.asarray(sample, dtype=np.float32)
        if x.shape == (int(np.prod(fm.sample_shape)),):
            x = x.reshape(fm.sample_shape)
        if tuple(x.shape) != fm.sample_shape:
            raise ValueError(
                f"sample shape {tuple(x.shape)} != model input "
                f"{fm.sample_shape} for {model!r}")
        if not self._accepting or self._closing:
            raise ServerClosed("fleet is shutting down")
        if deadline_ms is None:
            deadline_ms = self.cfg.default_deadline_ms
        if deadline_ms is not None and float(deadline_ms) <= 0.0:
            self._stats.bump("submitted")
            self._stats.bump("rejected_deadline")
            raise DeadlineExceeded(
                f"deadline {float(deadline_ms):g} ms is already "
                f"unmeetable at submit")
        t0 = now_s()
        req = _Request(
            sample=x, future=Future(), t_submit=t0,
            deadline=None if deadline_ms is None
            else t0 + float(deadline_ms) / 1e3,
            priority=priority)
        self._stats.bump("submitted")
        try:
            with span("fleet.submit", model=model) as sp:
                idx = self._sched.submit(req, wait=wait,
                                         timeout_s=wait_timeout_s)
                queued, inflight = self._sched.depth(idx)
                self._stats.observe_replica(idx, queued, inflight)
                sp.set(worker=idx, queued=self._sched.queued_total())
        except SchedulerFull:
            self._stats.bump("rejected_overload")
            raise ServerOverloaded(
                f"{model!r} fleet queue at depth {self.cfg.queue_depth}"
            ) from None
        except SchedulerClosed:
            raise ServerClosed("fleet is shutting down") from None
        return req.future

    def submit_many(self, model: str, samples, **kw) -> List[Future]:
        """Burst admission; per-sample rejections surface on the
        corresponding future (server.submit_many semantics)."""
        futs: List[Future] = []
        for s in samples:
            try:
                futs.append(self.submit(model, s, **kw))
            except ServingError as e:
                f: Future = Future()
                f.set_exception(e)
                futs.append(f)
        return futs

    def _require_model(self, name: str) -> FleetModel:
        fm = self._model
        if fm is None or fm.name != name:
            from .errors import ModelNotLoaded

            loaded = [] if fm is None else [fm.name]
            raise ModelNotLoaded(
                f"model {name!r} is not loaded in this fleet "
                f"(loaded: {loaded})")
        return fm

    @contextlib.contextmanager
    def _swap_lease(self):
        """Exclusive claim on the worker set for reload / respawn /
        scale.  The claim itself is condition-guarded (the wait releases
        `_swap_cv`); the leaseholder then blocks — spawn waits, reap
        ladders, probe RPCs — while holding NO mutex, so dispatch and
        observability never stall behind a multi-second swap."""
        with self._swap_cv:
            while self._swap_busy:
                self._swap_cv.wait(0.5)
            self._swap_busy = True
        try:
            yield
        finally:
            with self._swap_cv:
                self._swap_busy = False
                self._swap_cv.notify_all()

    # --------------------------------------------------------------- reload
    def reload(self, name: str) -> FleetModel:
        """Fleet-wide generation hot-swap with ZERO mixed-generation
        responses: close the dispatch gate, wait out in-flight batches
        (every response they carry is old-generation), reload every
        live worker, bump the fleet generation, reopen the gate.  The
        barrier makes the swap atomic from any client's point of view —
        the generation sequence across responses is monotone with one
        step.  A worker that fails its reload trips and respawns at the
        NEW generation (generation_base in its config)."""
        fm = self._require_model(name)
        with self._swap_lease():
            with self._flight_cv:
                self._swapping = True
                deadline = now_s() + max(self.cfg.ipc_deadline_s,
                                         self.cfg.result_timeout_s)
                while self._inflight > 0 and not self._closing:
                    remaining = deadline - now_s()
                    if remaining <= 0:
                        self._swapping = False
                        self._flight_cv.notify_all()
                        raise ServingError(
                            f"reload barrier timed out with "
                            f"{self._inflight} batches in flight")
                    self._flight_cv.wait(min(remaining, 0.5))
            try:
                live = [s for s in self._slots if s.state == "live"]
                new_gens = []
                for slot in live:
                    try:
                        meta, _ = self._call(
                            slot, {"cmd": "reload"},
                            timeout_s=self.cfg.ipc_deadline_s
                            + self.cfg.result_timeout_s)
                        if not meta.get("ok"):
                            raise ServingError(
                                f"worker {slot.idx} reload failed: "
                                f"{meta.get('detail', meta)}")
                        new_gens.append(int(meta["generation"]))
                    except Exception as e:
                        self._force_trip(slot.idx,
                                         f"reload: {type(e).__name__}")
                if not new_gens:
                    raise ServingError(
                        "reload failed on every live worker")
                gen = max(new_gens)
                with self._mu:
                    self._generation = gen
                self._event("fleet_reload", generation=gen,
                            workers=[s.idx for s in live],
                            reloaded=len(new_gens))
            finally:
                with self._flight_cv:
                    self._swapping = False
                    self._flight_cv.notify_all()
        return fm

    # ------------------------------------------------------------- batching
    def _run_batch(self, i: int, batch: List[_Request]) -> None:
        """Scheduler run callback — the server lane's _run_batch with
        the forward replaced by a framed round trip to worker i.  Never
        raises; every future resolves here."""
        now = now_s()
        live: List[_Request] = []
        for r in batch:
            r.t_pop = now
            if r.deadline is not None and now > r.deadline:
                self._stats.bump("rejected_deadline")
                r.future.set_exception(DeadlineExceeded(
                    f"deadline passed "
                    f"{round((now - r.deadline) * 1e3, 2)}"
                    f" ms before batch launch"))
            else:
                live.append(r)
        if not live:
            return
        # reload barrier: no dispatch may START while a generation swap
        # is in progress (in-flight count is what the swap waits out)
        with self._flight_cv:
            while self._swapping and not self._closing:
                self._flight_cv.wait(0.5)
            self._inflight += 1
        try:
            self._dispatch(i, live)
        finally:
            with self._flight_cv:
                self._inflight -= 1
                self._flight_cv.notify_all()

    def _dispatch(self, i: int, live: List[_Request]) -> None:
        slot = self._slots[i]
        plan = self.cfg.fault_plan
        kill_now = False
        inject_err = False
        spike_s = 0.0
        with self._mu:
            d = slot.dispatch
            slot.dispatch = d + 1
            state = slot.state
            pid = slot.pid
            if plan is not None:
                if (slot.incarnation == 0 and not slot.kill_fired
                        and plan.kill_at(i) is not None
                        and d >= plan.kill_at(i)):
                    slot.kill_fired = True
                    kill_now = True
                inject_err = plan.error_at(i, d)
                spike_s = plan.spike_ms(i, d) / 1e3
        queued, inflight = self._sched.depth(i)
        self._stats.observe_replica(i, queued, inflight, dispatched=1)
        err: Optional[Exception] = None
        meta: Dict[str, Any] = {}
        arrays: Dict[str, np.ndarray] = {}
        t_launch = now_s()
        try:
            if kill_now and pid is not None:
                # the drill's process-granularity fault: a REAL SIGKILL
                # to a live worker mid-burst; detection must flow
                # through the same machinery as a genuine crash
                with self._mu:
                    self._c["kills_injected"] += 1
                self._event("worker_kill_injected", worker=i,
                            dispatch=d, pid=pid)
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, OSError):
                    pass
            if spike_s > 0:
                time.sleep(spike_s)   # slow SUCCESS unless also erroring
            if inject_err:
                raise ServingError(
                    f"injected fault on worker {i} (ServeFaultPlan)")
            if state != "live":
                raise ipc.IpcError(f"worker {i} is {state}")
            with span("fleet.device", worker=i, live=len(live)):
                x = np.stack([r.sample for r in live]).astype(np.float32)
                meta, arrays = self._call(
                    slot,
                    {"cmd": "infer", "count": len(live),
                     "priorities": [r.priority for r in live]},
                    {"x": x},
                    timeout_s=self.cfg.ipc_deadline_s + spike_s)
            if not meta.get("ok"):
                raise ServingError(
                    f"worker {i} infer failed: "
                    f"{meta.get('detail', meta)}")
        except Exception as e:
            err = e
        if err is not None:
            self._record_error(i, reason=type(err).__name__)
            if not self._closing:
                retry = [r for r in live
                         if r.retries < self.cfg.max_retries]
                for r in retry:
                    r.retries += 1
                if retry:
                    try:
                        self._sched.requeue(retry, exclude=i)
                        with self._mu:
                            self._c["retried"] += len(retry)
                        kept = {id(r) for r in retry}
                        live = [r for r in live if id(r) not in kept]
                    except SchedulerClosed:
                        pass        # fall through: fail them below
            self._stats.bump("failed", len(live))
            for r in live:
                r.future.set_exception(ServingError(
                    f"fleet worker {i} failed: {err}"))
            return
        self._record_success(i)
        t_done = now_s()
        probs = arrays.get("probs")
        statuses = meta.get("statuses") or [None] * len(live)
        gens = meta.get("generations") or [0] * len(live)
        buckets = meta.get("buckets") or [0] * len(live)
        lives = meta.get("batch_live") or [0] * len(live)
        dms = meta.get("device_ms") or [0.0] * len(live)
        ok_rows = [j for j, st in enumerate(statuses) if st is None]
        if ok_rows:
            self._stats.observe_batch(len(ok_rows), max(
                buckets[j] for j in ok_rows))
        for j, r in enumerate(live):
            st = statuses[j] if j < len(statuses) else None
            if st is not None:
                self._stats.bump("failed")
                r.future.set_exception(ServingError(
                    f"fleet worker {i} rejected request: "
                    f"{st.get('error')}: {st.get('detail')}"))
                continue
            total_ms = (t_done - r.t_submit) * 1e3
            queue_wait_ms = (r.t_pop - r.t_submit) * 1e3
            assembly_ms = (t_launch - r.t_pop) * 1e3
            device_ms = float(dms[j]) if j < len(dms) else 0.0
            self._stats.observe_request(queue_wait_ms, assembly_ms,
                                        device_ms, total_ms)
            self._observe_total(r.priority, total_ms)
            r.future.set_result(Response(
                probs=np.asarray(probs[j]),
                model=self._model.name,
                generation=int(gens[j]),
                bucket=int(buckets[j]),
                batch_live=int(lives[j]),
                queue_wait_ms=round(queue_wait_ms, 4),
                assembly_ms=round(assembly_ms, 4),
                device_ms=round(device_ms, 4),
                total_ms=round(total_ms, 4),
                replica=i,
                priority=r.priority))

    def _observe_total(self, priority: str, total_ms: float) -> None:
        if priority != "interactive":
            return
        with self._mu:
            e = self._interactive_ewma_ms
            ewma = (float(total_ms) if e is None
                    else 0.8 * e + 0.2 * float(total_ms))
            self._interactive_ewma_ms = ewma
        self._stats.observe_sensors(interactive_ewma_ms=ewma)

    # ------------------------------------------------------------ transport
    def _next_seq(self) -> int:
        with self._seq_mu:
            self._seq += 1
            return self._seq

    def _call(self, slot: _Slot, meta: Dict[str, Any],
              arrays: Optional[Dict[str, np.ndarray]] = None, *,
              timeout_s: float) -> Tuple[Dict[str, Any],
                                         Dict[str, np.ndarray]]:
        """One framed round trip: register the reply slot, write the
        frame (writers serialized per pipe), wait (bounded) for the
        reader thread to route the reply.  A dead pipe or a timeout
        raises IpcError; the caller owns the breaker consequences."""
        proc = slot.proc
        if proc is None or proc.stdin is None:
            raise ipc.IpcClosed(f"worker {slot.idx} has no process")
        seq = self._next_seq()
        rq: "queue.Queue" = queue.Queue()
        with slot.pending_mu:
            slot.pending[seq] = rq
        try:
            ipc.write_frame(proc.stdin, dict(meta, seq=seq), arrays,
                            lock=slot.write_lock)
            try:
                reply = rq.get(timeout=timeout_s)
            except queue.Empty:
                raise ipc.IpcError(
                    f"worker {slot.idx} gave no reply within "
                    f"{timeout_s:.1f}s (seq {seq})")
            if isinstance(reply, Exception):
                raise reply
            return reply
        finally:
            with slot.pending_mu:
                slot.pending.pop(seq, None)

    def _reader(self, slot: _Slot, proc) -> None:
        """Per-worker reader thread: routes reply frames by seq.  On
        EOF/desync every waiting call fails immediately — a SIGKILL'd
        worker unblocks its dispatches in one pipe-close, not after the
        IPC deadline."""
        tag = f"fleet worker {slot.idx} stdout"
        while True:
            try:
                frame = ipc.read_frame(proc.stdout, what=tag)
            except (ipc.IpcError, ValueError, OSError) as e:
                self._fail_pending(slot, ipc.IpcClosed(f"{tag}: {e}"))
                return
            if frame is None:
                self._fail_pending(slot,
                                   ipc.IpcClosed(f"{tag}: worker exited"))
                return
            meta, arrays = frame
            with slot.pending_mu:
                rq = slot.pending.pop(meta.get("seq"), None)
            if rq is not None:
                rq.put((meta, arrays))

    def _fail_pending(self, slot: _Slot, exc: Exception) -> None:
        with slot.pending_mu:
            waiting = list(slot.pending.values())
            slot.pending.clear()
        for rq in waiting:
            rq.put(exc)

    # ----------------------------------------------------------- resilience
    def _record_success(self, i: int) -> None:
        with self._mu:
            self._breakers[i].record(True)

    def _record_error(self, i: int, *, reason: str) -> None:
        """One failed dispatch; trips on the rolling-window threshold,
        or immediately when the worker process is gone (a dead process
        fails every dispatch — no point burning min_samples more)."""
        slot = self._slots[i]
        with self._mu:
            if slot.state != "live":
                return              # already tripped/parked/respawning
            br = self._breakers[i]
            tripped = br.record(False)
            proc = slot.proc
            dead = proc is None or proc.poll() is not None
            if not tripped and dead and br.state == "closed":
                br.trip(now_s())
                tripped = True
        if tripped:
            self._trip_side_effects(i, reason)

    def _force_trip(self, i: int, reason: str) -> None:
        """Unconditional trip (heartbeat wedge, clean process exit,
        failed reload): the evidence is process-level, not a dispatch
        outcome, so the window doesn't apply."""
        with self._mu:
            if self._slots[i].state != "live":
                return
            br = self._breakers[i]
            if br.state == "closed":
                br.trip(now_s())
        self._trip_side_effects(i, reason)

    def _trip_side_effects(self, i: int, reason: str) -> None:
        """The open-breaker ritual, at process grain (mirrors
        ResilienceManager._open_side_effects): disable routing (never
        the last enabled slot), drain + requeue queued items
        exactly-once, make sure the process is really dead (a wedged
        one is killed so its reader EOFs and in-flight calls fail
        fast), and record the event."""
        slot = self._slots[i]
        with self._mu:
            self._c["trips"] += 1
            slot.state = "tripped"
            trips = self._breakers[i].trips
        disabled = self._sched.disable_unless_last(i)
        drained: List[_Request] = []
        if disabled:
            drained = self._sched.drain_replica(i)
            if drained:
                try:
                    self._sched.requeue(drained, exclude=i)
                    with self._mu:
                        self._c["requeued"] += len(drained)
                except SchedulerClosed:
                    for r in drained:
                        self._stats.bump("rejected_closed")
                        r.future.set_exception(ServerClosed(
                            "fleet closed before this request ran"))
        self._kill_slot_proc(slot)
        self._stats.observe_breaker(i, "open")
        self._event("worker_open", worker=i, trips=trips,
                    requeued=len(drained), reason=reason,
                    in_place=not disabled, pid=slot.pid)

    def _kill_slot_proc(self, slot: _Slot) -> None:
        """Make the slot's process dead for sure: SIGCONT first (a
        SIGSTOP'd worker can't die politely), then SIGKILL.  The reaper
        wait happens at respawn/close (ipc.reap)."""
        proc = slot.proc
        if proc is not None and proc.poll() is None:
            ipc.sigcont(proc.pid)
            try:
                proc.kill()
            except OSError:
                pass

    # ---------------------------------------------------------- maintenance
    def _loop(self) -> None:
        prev = now_s()
        while not self._stop_evt.wait(self.cfg.tick_s):
            now = now_s()
            dt, prev = now - prev, now
            try:
                self._tick(dt)
            except Exception as e:     # keep the control plane alive
                self._event("fleet_error",
                            error=f"{type(e).__name__}: {e}")

    def _tick(self, dt: float) -> None:
        # 1) detection: clean exits and heartbeat wedges on live slots
        for slot in self._slots:
            with self._mu:
                state, proc = slot.state, slot.proc
            if state != "live" or proc is None:
                continue
            if proc.poll() is not None:
                with self._mu:
                    self._c["proc_exits"] += 1
                self._force_trip(slot.idx,
                                 f"proc_exit rc={proc.poll()}")
                continue
            if self._watchdog.tick(slot.idx, slot.hb_path, dt):
                with self._mu:
                    self._c["hb_miss"] += 1
                self._force_trip(slot.idx, "heartbeat")
        # 2) recovery: cooled breakers respawn + probe for re-admission
        now = now_s()
        for slot in self._slots:
            with self._mu:
                br = self._breakers[slot.idx]
                actionable = (slot.state == "tripped"
                              and br.cooled_down(now))
                respawned = br.respawned
            if not actionable:
                continue
            with self._swap_lease():  # never race a reload's worker set
                if not respawned:
                    if not self._respawn(slot):
                        continue    # retry next tick
                self._probe_cycle(slot)
        # 3) autoscale
        if self._policy is not None and not self._closing:
            self._autoscale_tick()

    def _respawn(self, slot: _Slot) -> bool:
        """Fresh process for a tripped slot, warmed before re-admission
        is even attempted (the ready line follows load+warmup).  Spawned
        with generation_base = the CURRENT fleet generation, so a worker
        that died across a reload() comes back serving the new one."""
        if slot.proc is not None:
            ipc.reap(slot.proc, wait_s=2.0)
        try:
            self._spawn(slot)
            self._finish_spawn(slot, probing=True)
        except Exception as e:
            self._kill_slot_proc(slot)
            self._event("fleet_error", worker=slot.idx,
                        error=f"respawn failed: {type(e).__name__}: {e}")
            return False
        with self._mu:
            self._breakers[slot.idx].respawned = True
            self._c["respawns"] += 1
            self._c["restarts"] += 1
            incarnation = slot.incarnation
        self._event("worker_respawn", worker=slot.idx,
                    incarnation=incarnation, pid=slot.pid)
        return True

    def _probe_cycle(self, slot: _Slot) -> None:
        """Half-open probing: real end-to-end requests through the new
        process.  Probes draw from the SAME fault schedule as live
        traffic (dispatch index advances), so a worker inside an
        un-expired error storm keeps failing probes and re-opens —
        re-admission is earned, not granted."""
        i = slot.idx
        with self._mu:
            self._breakers[i].begin_probing()
            slot.state = "probing"
        self._stats.observe_breaker(i, "half_open")
        plan = self.cfg.fault_plan
        closed = False
        for _ in range(self.cfg.half_open_probes):
            with self._mu:
                d = slot.dispatch
                slot.dispatch = d + 1
                inject = (plan.error_at(i, d)
                          if plan is not None else False)
                spike_s = (plan.spike_ms(i, d) / 1e3
                           if plan is not None else 0.0)
            ok = not inject
            if ok:
                try:
                    if spike_s > 0:
                        time.sleep(spike_s)
                    meta, _ = self._call(
                        slot, {"cmd": "probe"},
                        timeout_s=self.cfg.ipc_deadline_s)
                    ok = bool(meta.get("ok"))
                except Exception:
                    ok = False
            with self._mu:
                br = self._breakers[i]
                if ok:
                    self._c["probes_ok"] += 1
                    closed = br.probe_ok()
                else:
                    self._c["probes_failed"] += 1
                    br.probe_fail(now_s())
                    slot.state = "tripped"
                state, streak = br.state, br.probe_successes
            self._event("worker_probe", worker=i, ok=ok,
                        state_after=state, streak=streak)
            if not ok:
                self._stats.observe_breaker(i, "open")
                return
        if closed:
            with self._mu:
                slot.state = "live"
            self._watchdog.reset(i)
            self._sched.set_enabled(i, True)
            self._stats.observe_breaker(i, "closed")

    # ------------------------------------------------------------ autoscale
    def _autoscale_tick(self) -> None:
        with self._mu:
            open_breakers = sum(1 for b in self._breakers
                                if b.state != "closed")
            ewma = self._interactive_ewma_ms
            parked = sum(1 for s in self._slots if s.state == "parked")
        pool = self.cfg.workers
        active = pool - parked
        qf = (self._sched.queued_total() / float(self.cfg.queue_depth)
              if self.cfg.queue_depth else 0.0)
        sample = SensorSample(queue_fraction=qf,
                              interactive_ewma_ms=ewma,
                              breakers_open=open_breakers)
        self._stats.observe_sensors(queue_fraction=qf,
                                    active_replicas=active)
        action, suppressed = self._policy.decide(sample, active=active,
                                                 pool=pool)
        if suppressed and action != "hold":
            self._event("scale_suppressed", action=action,
                        queue_fraction=round(qf, 4),
                        breakers_open=open_breakers)
            return
        if action == "up":
            self._scale_up(qf)
        elif action == "down":
            self._scale_down(qf)

    def _scale_up(self, qf: float) -> None:
        with self._mu:
            victim = next((s for s in self._slots
                           if s.state == "parked"), None)
        if victim is None:
            return
        with self._swap_lease():
            try:
                self._spawn(victim)
                self._finish_spawn(victim, probing=True)
            except Exception as e:
                self._kill_slot_proc(victim)
                self._event("fleet_error", worker=victim.idx,
                            error=f"scale-up spawn failed: "
                                  f"{type(e).__name__}: {e}")
                return
            with self._mu:
                victim.state = "live"
                self._c["scale_ups"] += 1
                self._c["restarts"] += 1
            self._watchdog.reset(victim.idx)
            self._sched.set_enabled(victim.idx, True)
        self._event("scale_up", worker=victim.idx, pid=victim.pid,
                    queue_fraction=round(qf, 4))

    def _scale_down(self, qf: float) -> None:
        """Park the highest healthy slot: disable routing (never the
        last), drain + requeue its queue, stop its process gracefully.
        The slot stays allocated — scale-up respawns into it."""
        with self._mu:
            victim = next(
                (s for s in reversed(self._slots)
                 if s.state == "live"
                 and self._breakers[s.idx].state == "closed"), None)
        if victim is None:
            return
        with self._swap_lease():
            if not self._sched.disable_unless_last(victim.idx):
                return
            drained = self._sched.drain_replica(victim.idx)
            if drained:
                try:
                    self._sched.requeue(drained, exclude=victim.idx)
                    with self._mu:
                        self._c["requeued"] += len(drained)
                except SchedulerClosed:
                    for r in drained:
                        self._stats.bump("rejected_closed")
                        r.future.set_exception(ServerClosed(
                            "fleet closed before this request ran"))
            with self._mu:
                victim.state = "parked"
                self._c["scale_downs"] += 1
            self._stop_worker(victim)
        self._event("scale_down", worker=victim.idx,
                    requeued=len(drained), queue_fraction=round(qf, 4))

    # -------------------------------------------------------------- spawning
    def _spawn(self, slot: _Slot) -> None:
        """Write the slot's config (generation_base = current fleet
        generation) and launch the worker with binary pipes.  The ready
        wait is separate (_finish_spawn) so load() can fan spawns out
        and overlap the workers' compile time."""
        with self._mu:
            gen_base = self._generation
        cfg = dict(self._model_cfg)
        cfg["worker"] = slot.idx
        cfg["generation_base"] = gen_base
        cfg["heartbeat_path"] = os.path.join(self.workdir,
                                             f"hb_f{slot.idx}")
        slot.cfg_path = os.path.join(self.workdir,
                                     f"fleet_worker_{slot.idx}.json")
        with open(slot.cfg_path, "w") as f:
            json.dump(cfg, f)
        slot.hb_path = cfg["heartbeat_path"]
        slot.stderr_path = os.path.join(
            self.workdir, f"fleet_worker_{slot.idx}.stderr")
        if slot.stderr_f is not None:
            try:
                slot.stderr_f.close()
            except OSError:
                pass
        slot.stderr_f = open(slot.stderr_path, "ab")
        proc = ipc.spawn_worker(_WORKER_MODULE, slot.cfg_path,
                                stderr_f=slot.stderr_f, text=False)
        with self._mu:
            slot.proc = proc
            slot.pid = proc.pid
            slot.incarnation += 1
            slot.state = "spawning"
        self._event("worker_spawn", worker=slot.idx, pid=proc.pid,
                    incarnation=slot.incarnation,
                    generation_base=gen_base)

    def _finish_spawn(self, slot: _Slot, *, probing: bool = False
                      ) -> None:
        """Bounded ready-wait, then start the reader thread.  The slot
        comes up 'live' at load time (the scheduler routes to it
        immediately) or stays out of routing when re-admission must be
        earned (probing=True: respawn / scale-up paths flip it after
        their probe cycle)."""
        ready = ipc.wait_ready_line(
            slot.proc, timeout_s=self.cfg.spawn_timeout_s,
            what=f"fleet worker {slot.idx}",
            stderr_path=slot.stderr_path)
        slot.ready = ready
        self._watchdog.reset(slot.idx)
        reader = threading.Thread(
            target=self._reader, args=(slot, slot.proc),
            name=f"sparknet-fleet-reader-{slot.idx}", daemon=True)
        slot.reader = reader
        reader.start()
        with self._mu:
            slot.state = "probing" if probing else "live"
        self._event("worker_ready", worker=slot.idx, pid=slot.pid,
                    incarnation=slot.incarnation,
                    compiles=ready.get("compiles"),
                    generation=ready.get("generation"))

    def _stop_worker(self, slot: _Slot) -> None:
        """Graceful stop: SIGCONT, polite stop frame, reap ladder, close
        pipes.  Safe on dead/parked slots."""
        proc = slot.proc
        if proc is None:
            return
        if proc.poll() is None:
            ipc.sigcont(proc.pid)
            try:
                ipc.write_frame(proc.stdin,
                                {"cmd": "stop", "seq": self._next_seq()},
                                lock=slot.write_lock)
            except ipc.IpcError:
                pass
        ipc.reap(proc)
        for stream in (proc.stdin, proc.stdout):
            try:
                if stream:
                    stream.close()
            except OSError:
                pass
        if slot.stderr_f is not None:
            try:
                slot.stderr_f.close()
            except OSError:
                pass

    # --------------------------------------------------------------- observe
    def kill_worker(self, i: int, sig: int = signal.SIGKILL) -> None:
        """Deliver a REAL signal to worker i (tests/chaos tooling).  The
        router marks nothing — detection must flow through the same
        poll/heartbeat/EOF machinery a genuine fault exercises."""
        pid = self._slots[i].pid
        if pid is None:
            raise ValueError(f"worker {i} has no process")
        os.kill(pid, sig)

    def worker_pid(self, i: int) -> Optional[int]:
        return self._slots[i].pid

    def all_closed(self) -> bool:
        with self._mu:
            return all(b.state == "closed" for b in self._breakers)

    def events_snapshot(self) -> List[dict]:
        with self._mu:
            return [dict(e) for e in self.events]

    def fleet_snapshot(self) -> Dict[str, object]:
        """JSON-ready control-plane state (the drill's accounting)."""
        with self._mu:
            return {
                "workers": self.cfg.workers,
                "live": sum(1 for s in self._slots
                            if s.state == "live"),
                "states": {str(s.idx): s.state for s in self._slots},
                "breakers": {str(i): self._breakers[i].state
                             for i in range(len(self._breakers))},
                "open_now": sum(1 for b in self._breakers
                                if b.state != "closed"),
                "incarnations": [s.incarnation for s in self._slots],
                "generation": self._generation,
                "interactive_ewma_ms": (
                    None if self._interactive_ewma_ms is None
                    else round(self._interactive_ewma_ms, 3)),
                "fault_plan": self.cfg.fault_plan is not None,
                **dict(self._c),
            }

    def stats(self) -> Dict[str, object]:
        """server.stats()-shaped snapshot: the model entry carries the
        standard ModelStats counters/latency summaries plus the fleet
        control plane under "fleet"."""
        fm = self._model
        per_model: Dict[str, Any] = {}
        if fm is not None:
            m = self._stats.snapshot()
            m["generation"] = self.generation
            m["engine_compiles"] = sum(
                int(s.ready.get("compiles") or 0) for s in self._slots)
            m["queued_now"] = (self._sched.queued_total()
                               if self._sched is not None else 0)
            breakdown = self._stats.replica_breakdown()
            if self._sched is not None:
                for i, (queued, inflight) in \
                        enumerate(self._sched.depths()):
                    entry = breakdown.setdefault(
                        str(i), {"queued_max": 0, "inflight_max": 0,
                                 "dispatches": 0})
                    entry["queued_now"] = queued
                    entry["inflight_now"] = inflight
                    entry["state"] = self._slots[i].state
                    entry["pid"] = self._slots[i].pid
            m["workers"] = breakdown
            m["fleet"] = self.fleet_snapshot()
            per_model[fm.name] = m
        return {
            "models": per_model,
            "config": {"workers": self.cfg.workers,
                       "max_batch": self.cfg.max_batch,
                       "max_wait_ms": self.cfg.max_wait_ms,
                       "queue_depth": self.cfg.queue_depth,
                       "min_fill": self.cfg.min_fill,
                       "default_deadline_ms":
                           self.cfg.default_deadline_ms,
                       "ipc_deadline_s": self.cfg.ipc_deadline_s,
                       "heartbeat_s": self.cfg.heartbeat_s,
                       "autoscale": self.cfg.autoscale is not None,
                       "fault_plan": self.cfg.fault_plan is not None},
            "accepting": self._accepting}

    # ---------------------------------------------------------------- events
    def _event(self, kind: str, **fields) -> None:
        """resilience.py's event discipline: wall-clock-free payload
        appended in memory and (optionally) as one JSONL line —
        DISTACC.md documents the schema per kind."""
        rec = {"kind": kind,
               "model": self._model.name if self._model else None}
        rec.update(fields)
        with self._mu:
            self.events.append(rec)
        path = self.cfg.event_log
        if path:
            with self._ev_mu:
                with open(path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
