"""Serving resilience control plane: per-replica circuit breakers,
SLO-aware admission shedding, and seeded serving fault injection.

Training got its fault story in two layers — partial-quorum masked
averaging (parallel/elastic.py) and the process supervisor
(elastic/proc.py) — both exercised by deterministic chaos
(elastic/chaos.py).  This module is the serving-side twin, applying the
same degrade-gracefully philosophy at the REQUEST layer, with
TensorFlow's device-failure/re-placement model (PAPERS.md) as the
blueprint: a replica is an evictable, respawnable placement, not a
fixed resource.

Three cooperating pieces, all owned per model lane by a
`ResilienceManager`:

- **CircuitBreaker** (one per replica slot): a rolling window of
  dispatch outcomes drives closed -> open -> half-open -> closed.  On
  trip, the manager disables the slot in the `ReplicaScheduler`,
  drains-and-requeues its pending items onto healthy replicas (the
  items were already admitted — requeueing bypasses queue_depth and
  never re-rejects), releases the device slot via
  `DevicePlacer.evict()`, and after a cooldown rebuilds a FRESH runner
  on the SAME device (`ModelRegistry.rebuild_replica` +
  `DevicePlacer.respawn`).  Re-admission is earned through half-open
  probes: seeded single-sample forwards through the fresh runner; N
  consecutive successes close the breaker, one failure re-opens it
  (without rebuilding again — the respawn already happened this
  episode).
- **SLO-aware shedding**: requests carry a priority class
  (``interactive`` | ``batch``).  When the lane's queue crosses
  `shed_fraction` of queue_depth, or the interactive total-latency EWMA
  exceeds `slo_ms`, BATCH requests are shed at admission with the 503
  overload taxonomy (errors.RequestShed) — interactive traffic keeps
  the queue.  Deadlines propagate the same way: a request already dead
  at submit is answered 504 immediately, and one dead at batch
  assembly is dropped before device time (both emit `deadline_drop`
  events).
- **ServeFaultPlan**: deterministic fault injection over the replica
  dispatch stream, reusing elastic/chaos.py's sha256 `u01` draw.
  Faults are keyed by (replica, dispatch index), never wall clock, so
  the SCHEDULE is bitwise-replayable across runs (`schedule_digest`
  pins it); live event interleavings naturally vary with thread
  timing.  Grammar (``ServeFaultPlan.from_spec``), comma tokens:

      errstorm:<replica>@<start>+<n>       n consecutive dispatch errors
      spike:<replica>@<start>+<n>x<ms>     n dispatches delayed by ms
      kill:<replica>@<dispatch>            hard kill: every dispatch
                                           fails until respawn
      flaky:<prob>                         per-dispatch error draw

  Malformed tokens die with a ValueError naming the token (the
  repo-wide parser contract).

Every state transition lands as a wall-clock-free JSONL event
(`replica_open` / `replica_probe` / `replica_respawn` / `shed` /
`deadline_drop`; schema table in DISTACC.md) mirroring
deploy/watcher.py's event discipline, and as breaker-state gauges in
the model's ModelStats registry.  The drill is
`scripts/serve_chaos_run.py` (ONE JSON line), smoked by
scripts/lint_gate.sh and landed by bench.py's `serving_resilience` leg.

Locking: the manager's `_mu` guards all mutable state and is NEVER
held across a forward, a probe, a rebuild, a scheduler call, or a
sleep (ANALYSIS.md R008); scheduler/placer/registry locks are acquired
only while `_mu` is free, so no lock-order cycle exists (R007).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..elastic.chaos import u01
from ..obs.trace import now_s

__all__ = [
    "ResilienceConfig", "CircuitBreaker", "ServeFaultPlan",
    "ResilienceManager", "PRIORITIES",
    "BREAKER_WINDOW_ENV", "BREAKER_ERRS_ENV", "BREAKER_COOLDOWN_ENV",
    "PROBES_ENV", "SLO_ENV", "SHED_FRACTION_ENV",
]

PRIORITIES = ("interactive", "batch")

BREAKER_WINDOW_ENV = "SPARKNET_SERVE_BREAKER_WINDOW"
BREAKER_ERRS_ENV = "SPARKNET_SERVE_BREAKER_ERRS"
BREAKER_COOLDOWN_ENV = "SPARKNET_SERVE_BREAKER_COOLDOWN_S"
PROBES_ENV = "SPARKNET_SERVE_PROBES"
SLO_ENV = "SPARKNET_SERVE_SLO_MS"
SHED_FRACTION_ENV = "SPARKNET_SERVE_SHED_FRACTION"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return int(default)
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an int")


def _devstr(device):
    """Event-field rendering for a placement: one device -> its str; a
    sharded replica's mesh slice (a device list) -> the list of strs."""
    if device is None:
        return None
    if isinstance(device, (list, tuple)):
        return [str(d) for d in device]
    return str(device)


# --------------------------------------------------------------- fault plan
@dataclasses.dataclass(frozen=True)
class ServeFaultPlan:
    """Seeded serving fault schedule — a pure function of
    (seed, replica, dispatch index), like elastic/chaos.py's FaultPlan
    is of (seed, round, slot): no wall clock or RNG state enters any
    decision, so two constructions from the same spec+seed agree
    bitwise on every draw (`schedule_digest` pins this; the overload
    soak and the drill replay it across two runs).

    storms: replica -> (start, n): dispatches [start, start+n) error.
    spikes: replica -> (start, n, ms): dispatches [start, start+n) are
        delayed by `ms` before launching (latency-fault path — the
        breaker sees slow successes, not errors).
    kills: replica -> dispatch index at which the replica hard-dies:
        every later dispatch errors until the control plane respawns
        it (incarnation bump clears the kill — a fresh runner is a
        fresh process in this model).
    flaky_prob: per-(replica, dispatch) independent error draw.
    """

    seed: int = 0
    storms: Dict[int, Tuple[int, int]] = dataclasses.field(
        default_factory=dict)
    spikes: Dict[int, Tuple[int, int, float]] = dataclasses.field(
        default_factory=dict)
    kills: Dict[int, int] = dataclasses.field(default_factory=dict)
    flaky_prob: float = 0.0

    def __post_init__(self):
        for r, (start, n) in self.storms.items():
            if start < 0 or n < 1:
                raise ValueError(
                    f"errstorm for replica {r} needs start >= 0 and "
                    f"n >= 1, got start={start} n={n}")
        for r, (start, n, ms) in self.spikes.items():
            if start < 0 or n < 1 or ms <= 0:
                raise ValueError(
                    f"spike for replica {r} needs start >= 0, n >= 1 "
                    f"and ms > 0, got start={start} n={n} ms={ms}")
        for r, d in self.kills.items():
            if d < 0:
                raise ValueError(f"kill dispatch for replica {r} must "
                                 f"be >= 0, got {d}")
        if not 0.0 <= self.flaky_prob <= 1.0:
            raise ValueError(f"flaky prob must be in [0, 1], "
                             f"got {self.flaky_prob}")

    # ------------------------------------------------------------- queries
    def error_at(self, replica: int, dispatch: int) -> bool:
        w = self.storms.get(int(replica))
        if w is not None and w[0] <= dispatch < w[0] + w[1]:
            return True
        if self.flaky_prob > 0.0:
            return u01(self.seed, "serve_err", int(replica),
                       int(dispatch)) < self.flaky_prob
        return False

    def spike_ms(self, replica: int, dispatch: int) -> float:
        w = self.spikes.get(int(replica))
        if w is not None and w[0] <= dispatch < w[0] + w[1]:
            return float(w[2])
        return 0.0

    def kill_at(self, replica: int) -> Optional[int]:
        d = self.kills.get(int(replica))
        return None if d is None else int(d)

    def decision(self, replica: int, dispatch: int) -> str:
        """Compact per-(replica, dispatch) fault decision — the unit the
        bitwise replay contract is defined over."""
        parts = []
        k = self.kill_at(replica)
        if k is not None and dispatch >= k:
            parts.append("k")
        if self.error_at(replica, dispatch):
            parts.append("e")
        ms = self.spike_ms(replica, dispatch)
        if ms > 0:
            parts.append(f"s{ms:g}")
        return "".join(parts) or "."

    def schedule_digest(self, n_replicas: int,
                        n_dispatches: int = 4096) -> str:
        """sha256 over every decision in the (replica, dispatch) grid —
        two same-seed plans must produce the identical digest (the
        drill's replay_bitwise check and the soak test pin it)."""
        h = hashlib.sha256()
        for r in range(int(n_replicas)):
            for d in range(int(n_dispatches)):
                h.update(self.decision(r, d).encode())
                h.update(b"|")
        return h.hexdigest()

    # -------------------------------------------------------------- parser
    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "ServeFaultPlan":
        """Parse the comma-separated token grammar (module docstring).
        Malformed tokens die with a ValueError naming the token, never
        an IndexError — the repo-wide parser contract."""
        storms: Dict[int, Tuple[int, int]] = {}
        spikes: Dict[int, Tuple[int, int, float]] = {}
        kills: Dict[int, int] = {}
        flaky = 0.0
        for raw in (t.strip() for t in (spec or "").split(",")):
            if not raw:
                continue
            kind, sep, rest = raw.partition(":")
            try:
                if kind == "errstorm" and sep:
                    rep, at, window = rest.partition("@")
                    start, plus, n = window.partition("+")
                    if not (at and plus):
                        raise ValueError("missing '@' or '+'")
                    storms[int(rep)] = (int(start), int(n))
                elif kind == "spike" and sep:
                    rep, at, window = rest.partition("@")
                    start, plus, tail = window.partition("+")
                    n, x, ms = tail.partition("x")
                    if not (at and plus and x):
                        raise ValueError("missing '@', '+' or 'x'")
                    spikes[int(rep)] = (int(start), int(n), float(ms))
                elif kind == "kill" and sep:
                    rep, at, d = rest.partition("@")
                    if not at:
                        raise ValueError("missing '@'")
                    kills[int(rep)] = int(d)
                elif kind == "flaky" and sep:
                    flaky = float(rest)
                else:
                    raise ValueError("unknown token kind")
            except ValueError as e:
                raise ValueError(
                    f"malformed serve chaos token {raw!r} in {spec!r}: "
                    f"{e} (grammar: errstorm:<r>@<start>+<n>, "
                    f"spike:<r>@<start>+<n>x<ms>, kill:<r>@<dispatch>, "
                    f"flaky:<p>)") from None
        return cls(seed=int(seed), storms=storms, spikes=spikes,
                   kills=kills, flaky_prob=flaky)


# ------------------------------------------------------------------ breaker
class CircuitBreaker:
    """closed -> open -> half-open -> closed over a rolling outcome
    window for ONE replica slot.

    Not thread-safe on its own: the ResilienceManager serializes every
    access under its `_mu` (the breaker is pure bookkeeping — all side
    effects of a transition live in the manager)."""

    def __init__(self, *, window: int, error_threshold: float,
                 min_samples: int, cooldown_s: float,
                 half_open_probes: int) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if not 0.0 < error_threshold <= 1.0:
            raise ValueError(f"error_threshold must be in (0, 1], "
                             f"got {error_threshold}")
        if min_samples < 1:
            raise ValueError(f"min_samples must be >= 1, "
                             f"got {min_samples}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        if half_open_probes < 1:
            raise ValueError(f"half_open_probes must be >= 1, "
                             f"got {half_open_probes}")
        self.window = int(window)
        self.error_threshold = float(error_threshold)
        self.min_samples = int(min_samples)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = int(half_open_probes)
        self.state = "closed"
        self.trips = 0
        self.opened_at = 0.0
        self.respawned = False      # this open episode already rebuilt
        self.probe_successes = 0
        self._outcomes: Deque[bool] = deque(maxlen=self.window)

    def record(self, ok: bool) -> bool:
        """One closed-state dispatch outcome; True when this outcome
        TRIPS the breaker (the caller then runs the open side effects —
        disable, drain, requeue, evict).  Outcomes landing while open or
        half-open (in-flight stragglers) are ignored: the episode's
        verdict now belongs to the probes."""
        if self.state != "closed":
            return False
        self._outcomes.append(bool(ok))
        n = len(self._outcomes)
        errs = n - sum(self._outcomes)
        if n >= self.min_samples and errs / n >= self.error_threshold:
            self.trip(now_s())
            return True
        return False

    def trip(self, now: float) -> None:
        self.state = "open"
        self.trips += 1
        self.opened_at = float(now)
        self.respawned = False
        self.probe_successes = 0
        self._outcomes.clear()

    def cooled_down(self, now: float) -> bool:
        return self.state == "open" and \
            now - self.opened_at >= self.cooldown_s

    def begin_probing(self) -> None:
        self.state = "half_open"
        self.probe_successes = 0

    def probe_ok(self) -> bool:
        """One successful half-open probe; True once the success streak
        closes the breaker."""
        self.probe_successes += 1
        if self.probe_successes >= self.half_open_probes:
            self.state = "closed"
            self._outcomes.clear()
            return True
        return False

    def probe_fail(self, now: float) -> None:
        """A failed half-open probe re-opens WITHOUT counting a new trip
        or re-rebuilding (`respawned` survives): the episode continues,
        the cooldown restarts."""
        self.state = "open"
        self.opened_at = float(now)
        self.probe_successes = 0

    def error_rate(self) -> float:
        n = len(self._outcomes)
        return 0.0 if n == 0 else (n - sum(self._outcomes)) / n


# ------------------------------------------------------------------- config
@dataclasses.dataclass
class ResilienceConfig:
    """Knobs of the serving resilience control plane.  Every default
    reads its serve env knob (the module-level *_ENV names, registered
    in analysis/knobs.py + the README table, R004) so deployments tune
    without code; explicit constructor values win."""

    breaker_window: int = dataclasses.field(
        default_factory=lambda: _env_int(BREAKER_WINDOW_ENV, 16))
    breaker_error_threshold: float = dataclasses.field(
        default_factory=lambda: _env_float(BREAKER_ERRS_ENV, 0.5))
    breaker_min_samples: int = 4
    cooldown_s: float = dataclasses.field(
        default_factory=lambda: _env_float(BREAKER_COOLDOWN_ENV, 0.25))
    half_open_probes: int = dataclasses.field(
        default_factory=lambda: _env_int(PROBES_ENV, 3))
    slo_ms: float = dataclasses.field(
        default_factory=lambda: _env_float(SLO_ENV, 500.0))
    shed_fraction: float = dataclasses.field(
        default_factory=lambda: _env_float(SHED_FRACTION_ENV, 0.5))
    max_retries: int = 2        # per-request redispatches after a
    #                             failed batch before its future errors
    tick_s: float = 0.02        # maintenance thread period
    probe_seed: int = 0         # health_probe input seed
    fault_plan: Optional[ServeFaultPlan] = None
    event_log: Optional[str] = None   # JSONL path (DISTACC.md schema)

    def __post_init__(self) -> None:
        if self.breaker_window < 1:
            raise ValueError(f"breaker_window must be >= 1, "
                             f"got {self.breaker_window}")
        if not 0.0 < self.breaker_error_threshold <= 1.0:
            raise ValueError(
                f"breaker_error_threshold must be in (0, 1], "
                f"got {self.breaker_error_threshold}")
        if self.breaker_min_samples < 1:
            raise ValueError(f"breaker_min_samples must be >= 1, "
                             f"got {self.breaker_min_samples}")
        if self.cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, "
                             f"got {self.cooldown_s}")
        if self.half_open_probes < 1:
            raise ValueError(f"half_open_probes must be >= 1, "
                             f"got {self.half_open_probes}")
        if self.slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {self.slo_ms}")
        if not 0.0 <= self.shed_fraction <= 1.0:
            raise ValueError(f"shed_fraction must be in [0, 1], "
                             f"got {self.shed_fraction}")
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {self.max_retries}")
        if self.tick_s <= 0:
            raise ValueError(f"tick_s must be > 0, got {self.tick_s}")


# ------------------------------------------------------------------ manager
class ResilienceManager:
    """Per-lane control plane: breakers + shed controller + fault
    injection + the maintenance thread that walks an open breaker
    through evict -> respawn -> half-open probes -> re-admission.

    Wiring (serving/server.py): the lane's run callback consults
    `on_dispatch` before each forward and reports outcomes via
    `record_success`/`record_error`; admission consults
    `should_shed_batch` and the deadline helpers.  The manager itself
    only ever calls OUT to the scheduler (set_enabled / drain_replica /
    requeue), the placer (evict / respawn), and the registry
    (rebuild_replica) — never the reverse — with `_mu` released, so the
    lock graph stays acyclic (ANALYSIS.md R007/R008)."""

    def __init__(self, *, model: str, sched, lm, registry,
                 placer=None, config: Optional[ResilienceConfig] = None,
                 ) -> None:
        self.cfg = config if config is not None else ResilienceConfig()
        self._model = str(model)
        self._sched = sched
        self._lm = lm
        self._registry = registry
        self._placer = placer
        self._plan = self.cfg.fault_plan
        n = lm.n_replicas
        self._n = n
        self._mu = threading.Lock()
        self._ev_mu = threading.Lock()   # serializes event-log appends
        self._breakers = [
            CircuitBreaker(window=self.cfg.breaker_window,
                           error_threshold=self.cfg.breaker_error_threshold,
                           min_samples=self.cfg.breaker_min_samples,
                           cooldown_s=self.cfg.cooldown_s,
                           half_open_probes=self.cfg.half_open_probes)
            for _ in range(n)]
        self._dispatch = [0] * n        # fault-plan index per replica
        self._incarnation = [0] * n     # respawns bump; clears kills
        self._dead = [False] * n        # hard-killed until respawn
        self._gate = None       # autoscaler activity gate (see setter)
        self._opened_episode_at: Dict[int, float] = {}
        self._recovery_s: Dict[int, float] = {}
        self._interactive_ewma_ms: Optional[float] = None
        self._sheds = 0
        self._sheds_by_priority = {p: 0 for p in PRIORITIES}
        self._deadline_drops = 0
        self._requeued = 0
        self._retried = 0
        self._respawns = 0
        self._probes_ok = 0
        self._probes_failed = 0
        self.events: List[dict] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"sparknet-resil-{model}",
            daemon=True)
        self._thread.start()

    # ---------------------------------------------------------- fault feed
    def on_dispatch(self, replica: int) -> Tuple[bool, float]:
        """Called by the run callback before each forward on `replica`:
        advances that replica's dispatch index through the fault plan
        and returns (inject_error, spike_sleep_s).  A hard kill latches
        `dead` — every subsequent dispatch errors until the respawn
        bumps the incarnation (a fresh runner is a fresh process)."""
        with self._mu:
            d = self._dispatch[replica]
            self._dispatch[replica] = d + 1
            if (self._plan is not None
                    and self._incarnation[replica] == 0
                    and not self._dead[replica]):
                k = self._plan.kill_at(replica)
                if k is not None and d >= k:
                    self._dead[replica] = True
            err = self._dead[replica] or (
                self._plan.error_at(replica, d)
                if self._plan is not None else False)
            spike_s = (self._plan.spike_ms(replica, d) / 1e3
                       if self._plan is not None else 0.0)
        return err, spike_s

    def set_activity_gate(self, gate) -> None:
        """Register `gate(replica) -> bool` (the autoscaler's
        `is_active`).  A False slot is administratively PARKED — scaled
        down, its device residency already released through the placer
        — so its dispatch outcomes (in-flight stragglers finishing
        after the drain) are ignored: a parked slot's breaker must stay
        closed, or the breaker's evict would double-count the
        autoscaler's and its respawn would re-acquire residency the
        autoscaler released.  Called BEFORE `_mu` is taken (the gate
        has its own lock; never nested with ours — R007)."""
        self._gate = gate

    def record_success(self, replica: int) -> None:
        if self._gate is not None and not self._gate(replica):
            return
        with self._mu:
            self._breakers[replica].record(True)

    def record_error(self, replica: int) -> None:
        """One failed dispatch.  A trip (rolling-window threshold, or
        immediately for a hard-killed replica) runs the open side
        effects OUTSIDE the lock: disable routing, drain + requeue the
        slot's pending items onto healthy replicas, release the device
        slot."""
        if self._gate is not None and not self._gate(replica):
            return
        with self._mu:
            br = self._breakers[replica]
            tripped = br.record(False)
            if (not tripped and self._dead[replica]
                    and br.state == "closed"):
                # a hard-killed replica fails every dispatch — trip NOW
                # instead of burning min_samples more batches on it
                br.trip(now_s())
                tripped = True
            if tripped:
                self._opened_episode_at[replica] = br.opened_at
        if tripped:
            self._open_side_effects(replica)

    def _open_side_effects(self, replica: int) -> None:
        # The LAST enabled replica of a lane is never drained: zero
        # enabled replicas would park every admitted item (scheduler
        # fallback routing) and hang submit(wait=True) until timeout.
        # The breaker opens anyway, but the slot RESPAWNS IN PLACE —
        # it keeps routing (degraded: dispatches fail and retry
        # loudly, bounded by max_retries) while the maintenance loop
        # walks the usual evict -> rebuild -> half-open-probe cycle;
        # the close-time re-enable is then a no-op.
        drained: List = []
        disabled = self._sched.disable_unless_last(replica)
        if disabled:
            drained = self._sched.drain_replica(replica)
            if drained:
                self._sched.requeue(drained, exclude=replica)
                with self._mu:
                    self._requeued += len(drained)
        device = None
        if self._placer is not None:
            try:
                device = self._placer.evict(self._model, replica)
            except ValueError:
                device = None   # single-replica lanes have no placement
        self._lm.stats.observe_breaker(replica, "open")
        with self._mu:
            trips = self._breakers[replica].trips
        self._event("replica_open", replica=replica, trips=trips,
                    requeued=len(drained), device=_devstr(device),
                    in_place=not disabled)

    # ------------------------------------------------------------ shedding
    def should_shed_batch(self, queued_total: int,
                          queue_depth: int) -> Optional[str]:
        """A non-None reason means a batch-class request must be shed
        NOW (admission raises RequestShed).  Interactive traffic is
        never shed — it only ever sees the plain overload 503 at a
        completely full queue."""
        self._lm.stats.observe_sensors(
            queue_fraction=queued_total / float(queue_depth))
        if queued_total >= self.cfg.shed_fraction * queue_depth:
            return (f"queue {queued_total}/{queue_depth} at or over "
                    f"shed fraction {self.cfg.shed_fraction}")
        with self._mu:
            ewma = self._interactive_ewma_ms
        if ewma is not None and ewma > self.cfg.slo_ms:
            return (f"interactive latency EWMA {ewma:.1f} ms over "
                    f"SLO {self.cfg.slo_ms:g} ms")
        return None

    def count_shed(self, priority: str, queued: int,
                   reason: str) -> None:
        with self._mu:
            self._sheds += 1
            self._sheds_by_priority[priority] = \
                self._sheds_by_priority.get(priority, 0) + 1
        self._event("shed", priority=priority, queued=queued,
                    reason=reason)

    def observe_total(self, priority: str, total_ms: float) -> None:
        """Completed-request latency feed for the shed controller; only
        the interactive class drives the EWMA the SLO is defined over."""
        if priority != "interactive":
            return
        with self._mu:
            e = self._interactive_ewma_ms
            ewma = (float(total_ms) if e is None
                    else 0.8 * e + 0.2 * float(total_ms))
            self._interactive_ewma_ms = ewma
        # the one-set-of-numbers contract: the EWMA the shed controller
        # acts on IS the gauge the autoscaler and operators read
        self._lm.stats.observe_sensors(interactive_ewma_ms=ewma)

    def count_deadline_drop(self, stage: str, late_ms: float,
                            replica: Optional[int] = None) -> None:
        with self._mu:
            self._deadline_drops += 1
        fields = {"stage": stage, "late_ms": round(float(late_ms), 3)}
        if replica is not None:
            fields["replica"] = replica
        self._event("deadline_drop", **fields)

    def count_retried(self, n: int) -> None:
        with self._mu:
            self._retried += int(n)

    # --------------------------------------------------------- maintenance
    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.tick_s):
            try:
                self._tick()
            except Exception as e:     # keep the control plane alive
                self._event("resilience_error",
                            error=f"{type(e).__name__}: {e}")

    def _tick(self) -> None:
        now = now_s()
        for i in range(self._n):
            with self._mu:
                br = self._breakers[i]
                actionable = br.cooled_down(now)
                respawned = br.respawned
            if not actionable:
                continue
            if not respawned:
                if not self._respawn(i):
                    continue        # retry next tick
            self._probe_cycle(i)

    def _respawn(self, i: int) -> bool:
        """Rebuild a fresh runner for slot i on its original device and
        re-acquire the placement residency.  The generation does NOT
        bump — same params, bitwise-identical math (reload() is the
        parameter-change path)."""
        device = None
        if self._placer is not None:
            try:
                device = self._placer.respawn(self._model, i)
            except ValueError:
                device = None
        try:
            self._registry.rebuild_replica(self._model, i)
        except Exception as e:
            self._event("resilience_error", replica=i,
                        error=f"rebuild failed: "
                              f"{type(e).__name__}: {e}")
            return False
        with self._mu:
            self._incarnation[i] += 1
            self._dead[i] = False
            self._breakers[i].respawned = True
            self._respawns += 1
            incarnation = self._incarnation[i]
        self._event("replica_respawn", replica=i,
                    incarnation=incarnation, device=_devstr(device))
        return True

    def _probe_cycle(self, i: int) -> None:
        """Half-open probing: up to `half_open_probes` seeded forwards
        through the fresh runner.  Probes draw from the SAME fault
        schedule as live traffic (they advance the dispatch index), so
        a replica inside an un-expired error storm keeps failing probes
        and re-opens — re-admission is earned, not granted."""
        with self._mu:
            self._breakers[i].begin_probing()
        self._lm.stats.observe_breaker(i, "half_open")
        runner, _gen = self._lm.replica_snapshot(i)
        closed = False
        for _ in range(self.cfg.half_open_probes):
            err, spike_s = self.on_dispatch(i)
            ok = not err
            if ok:
                try:
                    if spike_s > 0:
                        time.sleep(spike_s)
                    runner.health_probe(seed=self.cfg.probe_seed)
                except Exception:
                    ok = False
            with self._mu:
                if ok:
                    self._probes_ok += 1
                    closed = self._breakers[i].probe_ok()
                else:
                    self._probes_failed += 1
                    self._breakers[i].probe_fail(now_s())
                state = self._breakers[i].state
                streak = self._breakers[i].probe_successes
            self._event("replica_probe", replica=i, ok=ok,
                        state_after=state, streak=streak)
            if not ok:
                self._lm.stats.observe_breaker(i, "open")
                return
        if closed:
            self._sched.set_enabled(i, True)
            self._lm.stats.observe_breaker(i, "closed")
            with self._mu:
                t0 = self._opened_episode_at.pop(i, None)
                if t0 is not None:
                    self._recovery_s[i] = now_s() - t0

    # ------------------------------------------------------------- observe
    def snapshot(self) -> Dict[str, object]:
        """JSON-ready control-plane state for server.stats() and the
        drill's accounting checks."""
        with self._mu:
            return {
                "breakers": {str(i): self._breakers[i].state
                             for i in range(self._n)},
                "trips": sum(b.trips for b in self._breakers),
                "open_now": sum(1 for b in self._breakers
                                if b.state != "closed"),
                "respawns": self._respawns,
                "incarnations": list(self._incarnation),
                "probes_ok": self._probes_ok,
                "probes_failed": self._probes_failed,
                "sheds": self._sheds,
                "sheds_by_priority": dict(self._sheds_by_priority),
                "deadline_drops": self._deadline_drops,
                "requeued": self._requeued,
                "retried": self._retried,
                "recovery_s": {str(i): round(v, 3)
                               for i, v in sorted(
                                   self._recovery_s.items())},
                "interactive_ewma_ms": (
                    None if self._interactive_ewma_ms is None
                    else round(self._interactive_ewma_ms, 3)),
                "fault_plan": self._plan is not None,
            }

    def events_snapshot(self) -> List[dict]:
        with self._mu:
            return [dict(e) for e in self.events]

    def all_closed(self) -> bool:
        with self._mu:
            return all(b.state == "closed" for b in self._breakers)

    def breaker_state(self, i: int) -> str:
        """One slot's breaker state ('closed'|'open'|'half_open') —
        the autoscaler's eligibility query: a non-closed slot is the
        BREAKER's to evict/respawn, never a scale victim or a scale-up
        candidate (no double-counting)."""
        with self._mu:
            return self._breakers[int(i)].state

    def open_breakers(self) -> int:
        """Count of non-closed breakers — the autoscaler's errstorm
        sensor: any open breaker suppresses scale-up (error-dominated
        load is the breaker's job, not the autoscaler's)."""
        with self._mu:
            return sum(1 for b in self._breakers
                       if b.state != "closed")

    def interactive_ewma(self) -> Optional[float]:
        """The interactive total-latency EWMA (ms; None before the
        first completed interactive request) — the shared SLO sensor
        the autoscaler reads."""
        with self._mu:
            return self._interactive_ewma_ms

    # ----------------------------------------------------------- lifecycle
    def stop(self) -> None:
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=30.0)

    # -------------------------------------------------------------- events
    def _event(self, kind: str, **fields) -> None:
        """deploy/watcher.py's event discipline: wall-clock-free payload
        appended to the in-memory list and (optionally) one JSONL line —
        DISTACC.md documents the schema per kind."""
        rec = {"kind": kind, "model": self._model}
        rec.update(fields)
        with self._mu:
            self.events.append(rec)
        path = self.cfg.event_log
        if path:
            with self._ev_mu:
                with open(path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
