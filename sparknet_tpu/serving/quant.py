"""Quantized serving forward: param-tree plumbing + calibration.

Modes (ModelRunner(..., quant=...), registry.load, `sparknet serve
--quant`, bench.py serving_int8 leg):

- "fp32" (default): the stock path, untouched.
- "bf16": every floating param and the activations cast to bfloat16;
  output scores cast back to f32.  Halves param HBM and rides the TPU's
  native bf16 compute paths.
- "int8": weight-only w8a16 — every floating param with ndim >= 2
  (conv OIHW, inner-product (out, in), attention mats) stored as
  per-output-channel symmetric int8 (ops/quant.py), dequantized to
  bf16 INSIDE the jitted forward (so HBM traffic is int8 + one f32
  scale vector per weight; the dequant fuses into the consumer on TPU);
  1-D floats (biases, BN stats) ride as bf16, activations bf16.

The fp32 master params are kept on the runner regardless, so
calibration, get_weights interchange, and hot-reload never touch the
quantized copies.  Calibration = top-1 agreement vs the fp32 forward on
seeded synthetic batches at load (ModelRunner.warmup); a
`quant_min_agreement` floor turns a silently-broken quantization into a
loud load failure.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

QUANT_MODES = ("fp32", "bf16", "int8")


def validate_quant_mode(mode: Optional[str]) -> str:
    mode = mode or "fp32"
    if mode not in QUANT_MODES:
        raise ValueError(
            f"quant mode {mode!r}; expected one of {QUANT_MODES}")
    return mode


def build_quantized_params(params: Dict, mode: str) -> Tuple[Dict, object]:
    """params (f32 master) -> (qtree, dequant_fn).

    qtree is a jit-traversable pytree: arrays, plus
    {"q": int8, "scale": f32} leaves-of-dicts for int8-packed weights.
    `dequant_fn(qtree)` rebuilds a {key: array} dict in the compute
    dtype inside the jitted forward.  mode "fp32" returns the params
    untouched with an identity dequant."""
    import jax.numpy as jnp

    from ..ops.quant import dequantize_int8, quantize_per_channel_int8

    if mode == "fp32":
        return dict(params), (lambda t: t)

    compute_dtype = jnp.bfloat16
    qtree: Dict = {}
    packed = set()
    for key, val in params.items():
        if not jnp.issubdtype(val.dtype, jnp.floating):
            qtree[key] = val  # int params (if any) pass through
        elif mode == "int8" and val.ndim >= 2:
            q, scale = quantize_per_channel_int8(val, axis=0)
            qtree[key] = {"q": q, "scale": scale}
            packed.add(key)
        else:
            qtree[key] = val.astype(compute_dtype)

    def dequant(tree: Dict) -> Dict:
        out = {}
        for key, val in tree.items():
            if key in packed:
                out[key] = dequantize_int8(val["q"], val["scale"], axis=0,
                                           dtype=compute_dtype)
            else:
                out[key] = val
        return out

    return qtree, dequant


def quantized_bytes(qtree: Dict) -> int:
    """Device bytes of the (possibly packed) param tree — the HBM win
    the mode buys, surfaced in ModelRunner.describe()."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(qtree):
        total += int(leaf.size) * int(leaf.dtype.itemsize)
    return total
