"""The `serve` CLI verb: JSONL-in, JSONL-out online scoring — the
no-egress stand-in for a network front-end (requests arrive on stdin or
a file instead of a socket; everything behind admission is the real
serving engine).

    python -m sparknet_tpu.cli serve --model lenet < requests.jsonl

Request lines:  {"id": 7, "data": [[...]]}   # CHW (or flat) sample
                # optional per-request fields: "priority":
                # "interactive"|"batch" (SLO-aware shedding with
                # --resilience) and "deadline_ms": 50 (overrides
                # --deadline_ms; <= 0 is answered 504 immediately)
Response lines: {"id": 7, "argmax": 3, "probs": [...], "bucket": 4,
                 "total_ms": 1.9}            # input order preserved
Rejections:     {"id": 7, "error": "DeadlineExceeded", "status": 504}

Compound lanes (`--model_type detect|featurize`, serving/compound.py)
additionally accept per-line proposal windows — one image fanning out
to N scored rows with all-or-nothing assembly:

    {"id": 9, "data": [[...]], "windows": [[x1, y1, x2, y2], ...]}
    -> {"id": 9, "mode": "detect", "n_windows": 3, "detections":
        [{"window": [...], "class": 7, "score": 1.3}, ...],
        "buckets": [2], "total_ms": 4.0}

and featurize lanes (require --capture_blob) answer with the
intermediate activations; without "windows" the "data" field is the
raw (N, C, H, W) row batch itself:

    {"id": 3, "data": [[[...]]]}
    -> {"id": 3, "mode": "featurize", "rows": 4, "feature_dim": 500,
        "features": [[...], ...], "buckets": [4], "total_ms": 2.2}

SIGINT triggers a graceful drain via utils/signals.py (the solver's
signal contract, reapplied to serving): stop admitting, deliver every
admitted request, exit 0.
"""

from __future__ import annotations

import json
import sys
from collections import deque
from typing import Optional

import numpy as np


def _parse_buckets(text: Optional[str]):
    if not text:
        return None
    try:
        return [int(t) for t in text.replace(" ", "").split(",") if t]
    except ValueError:
        raise SystemExit(f"--buckets must be comma-separated ints, "
                         f"got {text!r}")


def _open(path: str, mode: str):
    if path == "-":
        return (sys.stdin if "r" in mode else sys.stdout), False
    return open(path, mode), True


def _error_line(rid, exc) -> dict:
    from .errors import ServingError

    if isinstance(exc, ServingError):
        return {"id": rid, "error": type(exc).__name__,
                "status": exc.status, "detail": str(exc)}
    return {"id": rid, "error": type(exc).__name__, "status": 500,
            "detail": str(exc)}


def _build_fleet(args):
    """--fleet N: the OS-process router (serving/fleet.py) in place of
    the in-process server.  The fleet carries its own process-grained
    resilience and autoscale planes, so the in-process flags that would
    double-arm them are rejected rather than silently ignored."""
    from .fleet import FleetConfig, FleetServer

    if args.resilience or args.autoscale:
        raise SystemExit(
            "serve: --fleet workers have their own process-grained "
            "breaker/autoscale plane; drop --resilience/--autoscale "
            "(scale the fleet with --fleet N)")
    if args.replicas is not None:
        raise SystemExit(
            "serve: --fleet replaces --replicas (each worker process "
            "IS a full replica; use --shards for mesh slices per "
            "worker)")
    try:
        fcfg = FleetConfig(workers=args.fleet,
                           max_batch=args.max_batch,
                           max_wait_ms=args.max_wait_ms,
                           queue_depth=args.queue_depth,
                           default_deadline_ms=args.deadline_ms)
        if args.min_fill is not None:
            fcfg.min_fill = args.min_fill
            fcfg.__post_init__()    # re-validate the overridden field
    except ValueError as e:
        raise SystemExit(f"serve: {e}")
    return FleetServer(fcfg)


def cmd_serve(args) -> int:
    from ..utils.signals import SignalHandler, SolverAction
    from .server import InferenceServer, ServerConfig

    if getattr(args, "fleet", None):
        if args.model_type != "classify":
            raise SystemExit(
                "serve: --fleet workers speak plain classify only; "
                "compound lanes (--model_type detect|featurize) run "
                "in-process")
        server = _build_fleet(args)
        name = args.name or "default"
        try:
            fm = server.load(name, args.model, weights=args.weights,
                             buckets=_parse_buckets(args.buckets),
                             seed=args.seed, quant=args.quant,
                             quant_min_agreement=(
                                 args.quant_min_agreement
                                 if args.quant != "fp32" else None),
                             shards=args.shards)
        except (ValueError, RuntimeError) as e:
            raise SystemExit(f"serve: {e}")
        quant_note = "" if fm.quant == "fp32" else f", quant {fm.quant}"
        shard_note = "" if fm.shards <= 1 else f" x {fm.shards} shards"
        print(f"serving {args.model!r} as {name!r}: input "
              f"{fm.sample_shape}, buckets {fm.buckets}, "
              f"{fm.n_replicas} worker process(es){shard_note}"
              f"{quant_note}", file=sys.stderr, flush=True)
        return _serve_loop(args, server, name, fm.sample_shape)

    cfg = ServerConfig(max_batch=args.max_batch,
                       max_wait_ms=args.max_wait_ms,
                       queue_depth=args.queue_depth,
                       default_deadline_ms=args.deadline_ms)
    if args.min_fill is not None:
        cfg.min_fill = args.min_fill
    if args.resilience:
        from .resilience import ResilienceConfig

        rcfg = ResilienceConfig()
        if args.slo_ms is not None:
            rcfg.slo_ms = args.slo_ms
        cfg.resilience = rcfg
    if args.autoscale:
        from .autoscale import AutoscaleConfig

        try:
            acfg = AutoscaleConfig()
            if args.scale_min is not None:
                acfg.min_replicas = args.scale_min
            if args.slo_ms is not None:
                acfg.slo_ms = args.slo_ms
            acfg.__post_init__()    # re-validate the overridden fields
        except ValueError as e:
            raise SystemExit(f"serve: {e}")
        cfg.autoscale = acfg
    server = InferenceServer(cfg)
    name = args.name or "default"
    try:
        lm = server.load(name, args.model, weights=args.weights,
                         buckets=_parse_buckets(args.buckets),
                         seed=args.seed, quant=args.quant,
                         quant_min_agreement=(args.quant_min_agreement
                                              if args.quant != "fp32"
                                              else None),
                         replicas=args.replicas, shards=args.shards,
                         model_type=args.model_type,
                         capture_blob=args.capture_blob)
    except ValueError as e:
        # a failed quant calibration floor (or bad spec) is a load
        # error, not a crash
        raise SystemExit(f"serve: {e}")
    quant_note = ""
    if lm.runner.quant != "fp32":
        quant_note = (f", quant {lm.runner.quant} "
                      f"(top-1 agreement {lm.runner.quant_agreement:.4f})")
    shard_note = ""
    if lm.runner.shards > 1:
        shard_note = f" x {lm.runner.shards} shards"
    if args.model_type != "classify":
        cap = (f" capturing {lm.runner.capture_blob!r}"
               if lm.runner.capture_blob else "")
        shard_note += f", {args.model_type} lane{cap}"
    print(f"serving {args.model!r} as {name!r}: input "
          f"{lm.runner.sample_shape}, buckets {lm.runner.buckets}, "
          f"{lm.n_replicas} replica(s){shard_note}, "
          f"{lm.runner.compile_count()} programs warmed{quant_note}",
          file=sys.stderr, flush=True)
    return _serve_loop(args, server, name, lm.runner.sample_shape)


def _serve_loop(args, server, name: str, sample_shape) -> int:
    """The JSONL request/response pump, shared by the in-process and
    --fleet paths (both speak submit/close/stats)."""
    from ..utils.signals import SignalHandler, SolverAction

    pre = None
    if args.preprocess:
        from ..classify import Preprocessor

        crop = sample_shape[1:]
        image_dims = ([int(d) for d in args.image_dims.split(",")]
                      if args.image_dims else crop)
        pre = Preprocessor(image_dims, crop)

    handler = SignalHandler(SolverAction.STOP, SolverAction.NONE).install()
    fin, close_in = _open(args.input, "r")
    fout, close_out = _open(args.output, "w")
    pending: deque = deque()  # (id, Future | ready error dict), input order
    n_in = 0

    def flush(block: bool) -> None:
        while pending:
            rid, item = pending[0]
            if isinstance(item, dict):
                line = item
            elif item.done() or block:
                try:
                    r = item.result()
                    if hasattr(r, "fragments"):     # CompoundResponse
                        line = {"id": rid, "mode": r.mode,
                                "buckets": r.buckets,
                                "total_ms": r.total_ms}
                        if r.mode == "detect":
                            line["n_windows"] = r.fragments
                            line["detections"] = [
                                {"window": list(d["window"]),
                                 "class": d["class"],
                                 "score": d["score"]}
                                for d in (r.detections or [])]
                        else:
                            feats = np.asarray(r.features, np.float64)
                            line["rows"] = r.fragments
                            line["feature_dim"] = int(feats.shape[1])
                            line["features"] = feats.tolist()
                    else:
                        line = {"id": rid, "argmax": r.argmax,
                                "probs": np.asarray(r.probs, np.float64)
                                .tolist(),
                                "bucket": r.bucket,
                                "total_ms": r.total_ms}
                except Exception as e:
                    line = _error_line(rid, e)
            else:
                return
            pending.popleft()
            fout.write(json.dumps(line) + "\n")
            fout.flush()

    drained_early = False
    try:
        for raw in fin:
            if handler.get_requested_action() is SolverAction.STOP:
                drained_early = True
                break
            raw = raw.strip()
            if not raw:
                continue
            n_in += 1
            rid = None
            try:
                obj = json.loads(raw)
                rid = obj.get("id", n_in)
                data = np.asarray(obj["data"], dtype=np.float32)
                if pre is not None:
                    data = pre.one(data)
                kw = {}
                if "deadline_ms" in obj:
                    kw["deadline_ms"] = float(obj["deadline_ms"])
                model_type = getattr(args, "model_type", "classify")
                if model_type != "classify":
                    # compound lane: "windows" fans one image out to N
                    # scored rows (detect/featurize); without windows
                    # the data IS the raw row batch (featurize)
                    fut = server.submit_compound(
                        name, data, obj.get("windows"),
                        wait=(args.overload == "wait"),
                        priority=obj.get("priority", "interactive"),
                        context_pad=getattr(args, "context_pad", 0),
                        **kw)
                else:
                    fut = server.submit(
                        name, data,
                        wait=(args.overload == "wait"),
                        priority=obj.get("priority", "interactive"),
                        **kw)
                pending.append((rid, fut))
            except Exception as e:
                # a malformed or rejected REQUEST gets an error response
                # line; only the server itself dying should kill the
                # stream
                pending.append((rid if rid is not None else n_in,
                                _error_line(rid, e)))
            # keep memory bounded: resolve the head once the window of
            # outstanding work exceeds a few queues' worth
            if len(pending) > 4 * args.queue_depth:
                flush(block=True)
            else:
                flush(block=False)
        flush(block=True)  # graceful drain: every admitted request lands
    finally:
        server.close(drain=True)
        stats = server.stats()
        if args.stats_out:
            with open(args.stats_out, "w") as f:
                json.dump(stats, f, indent=2)
        m = stats["models"][name]
        shed_note = (f"{m['rejected_shed']} shed, "
                     if args.resilience else "")
        print(f"served {m['completed']}/{n_in} requests "
              f"({m['rejected_overload']} overloaded, {shed_note}"
              f"{m['rejected_deadline']} past deadline; "
              f"p50 {m['total_ms']['p50_ms']} ms, "
              f"p99 {m['total_ms']['p99_ms']} ms, "
              f"occupancy {m['batch_occupancy_mean']}, "
              f"{m['engine_compiles']} compiles"
              + (", drained on signal" if drained_early else ""),
              file=sys.stderr, flush=True)
        if close_in:
            fin.close()
        if close_out:
            fout.close()
        handler.uninstall()
    return 0


def register(sub) -> None:
    s = sub.add_parser(
        "serve", help="online JSONL scoring via the micro-batching "
                      "inference server (serving/)")
    s.add_argument("--model", required=True,
                   help="model-zoo name (e.g. lenet) or deploy .prototxt")
    s.add_argument("--weights", help=".npz / .caffemodel / .h5 warm start")
    s.add_argument("--name", help="registry name (default: 'default')")
    s.add_argument("--input", default="-",
                   help="JSONL request file, '-' for stdin")
    s.add_argument("--output", default="-",
                   help="JSONL response file, '-' for stdout")
    s.add_argument("--max_batch", type=int, default=8)
    s.add_argument("--max_wait_ms", type=float, default=5.0)
    s.add_argument("--queue_depth", type=int, default=64)
    s.add_argument("--fleet", type=int, metavar="N",
                   help="serve through N OS worker processes behind "
                        "one router (serving/fleet.py) instead of "
                        "in-process replicas; each worker runs a full "
                        "inference stack (replaces --replicas; "
                        "process-grained breakers built in)")
    s.add_argument("--replicas", type=int,
                   help="model replicas spread across the device mesh "
                        "(0 = one per device; default "
                        "SPARKNET_SERVE_REPLICAS, normally 1)")
    s.add_argument("--shards", type=int,
                   help="devices per replica SLICE (gspmd-sharded "
                        "params; 1 = unsharded; with --replicas 0, "
                        "one replica per slice; default "
                        "SPARKNET_SERVE_SHARDS, normally 1)")
    s.add_argument("--min_fill", type=int,
                   help="rows a replica waits for (up to max_wait_ms) "
                        "before dispatching; default "
                        "SPARKNET_SERVE_MIN_FILL, normally 1 = "
                        "continuous batching")
    s.add_argument("--deadline_ms", type=float,
                   help="per-request deadline; expired requests get a "
                        "504-style error line")
    s.add_argument("--buckets",
                   help="comma-separated batch buckets (default: powers "
                        "of two up to max_batch)")
    s.add_argument("--overload", default="wait",
                   choices=["wait", "reject"],
                   help="full queue: block the reader (wait) or emit "
                        "503-style error lines (reject)")
    s.add_argument("--resilience", action="store_true",
                   help="arm the resilience control plane "
                        "(serving/resilience.py): per-replica circuit "
                        "breakers + SLO-aware shedding of batch-"
                        "priority requests")
    s.add_argument("--slo_ms", type=float,
                   help="interactive latency SLO the shed controller "
                        "protects (with --resilience; default "
                        "SPARKNET_SERVE_SLO_MS)")
    s.add_argument("--autoscale", action="store_true",
                   help="arm the SLO-driven autoscaler "
                        "(serving/autoscale.py): --replicas becomes "
                        "the slot POOL and the active subset grows/"
                        "shrinks with load (scale knobs in the README "
                        "table)")
    s.add_argument("--scale_min", type=int,
                   help="autoscaler capacity floor (with --autoscale; "
                        "default SPARKNET_SERVE_SCALE_MIN, normally 1)")
    s.add_argument("--model_type", default="classify",
                   choices=["classify", "detect", "featurize"],
                   help="lane semantics (serving/compound.py): classify "
                        "= plain rows; detect = per-line proposal "
                        "windows warped + scored through the deploy "
                        "net's raw head with host-side NMS; featurize "
                        "= rows answered with --capture_blob "
                        "activations")
    s.add_argument("--capture_blob",
                   help="intermediate blob to read back as the answer "
                        "(required with --model_type featurize; the "
                        "engine's capture_blob exec variant)")
    s.add_argument("--context_pad", type=int, default=0,
                   help="context padding pixels around each window "
                        "before the warp (R-CNN geometry; with "
                        "--model_type detect)")
    s.add_argument("--preprocess", action="store_true",
                   help="treat 'data' as an HWC image: resize + center "
                        "crop to the model input (classify.Preprocessor)")
    s.add_argument("--image_dims",
                   help="H,W to resize to before the crop "
                        "(with --preprocess)")
    s.add_argument("--quant", default="fp32",
                   choices=["fp32", "bf16", "int8"],
                   help="serving forward numerics (serving/quant.py): "
                        "bf16 casts params+activations, int8 packs "
                        "weights per-channel (w8a16)")
    s.add_argument("--quant_min_agreement", type=float, default=0.99,
                   help="minimum top-1 agreement vs fp32 at calibration "
                        "(non-fp32 --quant only); below it the load "
                        "fails")
    s.add_argument("--seed", type=int, default=0,
                   help="param init seed when no --weights")
    s.add_argument("--stats_out",
                   help="write server.stats() JSON here on exit")
    s.set_defaults(fn=cmd_serve)
