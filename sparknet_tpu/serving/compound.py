"""Compound-request serving: windowed detection and featurization as
first-class served workloads.

A classify request is one sample -> one score row.  A COMPOUND request
is one logical unit that expands to N device rows: an image plus N
R-CNN proposal windows (model_type=detect — each window is context-
padded, warped, and scored through the deploy net's raw classifier
head, reference heritage: caffe/python/caffe/detector.py windowed
detection over window_data_layer.cpp geometry), or N raw samples whose
INTERMEDIATE activations are the answer (model_type=featurize — the
engine's capture_blob exec variant, the served replacement for
apps/featurizer_app.py's ad-hoc jit).

The fan-out rides the existing lane machinery untouched: every
fragment is an ordinary scheduler item, so it routes least-loaded,
batches into warmed buckets, sheds, retries, and breaker-trips like
any other row.  What this module adds is the COMPOUND semantics on
top:

- window ingress validation (the file-format parser contract applied
  to a network surface: malformed windows die with a request-naming
  ValueError, never an IndexError),
- the warp/preprocess path shared verbatim with the offline
  WindowDataFeed (data/window_data.py expand_window + _warp, mirror
  off — mirroring is a training augmentation), which is what makes
  served detection bitwise-equal to the offline batch path,
- host-side greedy NMS over the per-class scores (SVM margins for
  rcnn_ilsvrc13 — the deploy net has no softmax),
- the all-or-nothing fan-in assembler: per-image results reassemble in
  window order from a SINGLE generation, and the first fragment
  rejection (503/504) aborts the whole compound — queued sibling
  fragments are discarded before a worker pops them (no wasted device
  work), and the client never sees a partial or mixed-generation
  response.

Knobs: SPARKNET_SERVE_MAX_WINDOWS caps the fan-out width one request
may demand; SPARKNET_SERVE_COMPOUND_LOG appends one JSONL event per
compound lifecycle edge (schema: DISTACC.md "Compound serving
events").
"""

from __future__ import annotations

import json
import os
import threading
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.window_data import _warp, expand_window
from ..obs.trace import now_s

__all__ = ["MODEL_TYPES", "validate_model_type", "resolve_max_windows",
           "parse_windows", "warp_windows", "nms", "nms_detections",
           "CompoundResponse", "CompoundEventLog",
           "MAX_WINDOWS_ENV", "COMPOUND_LOG_ENV"]

MODEL_TYPES = ("classify", "detect", "featurize")

MAX_WINDOWS_ENV = "SPARKNET_SERVE_MAX_WINDOWS"
COMPOUND_LOG_ENV = "SPARKNET_SERVE_COMPOUND_LOG"


def validate_model_type(model_type: str) -> str:
    if model_type not in MODEL_TYPES:
        raise ValueError(f"model_type must be one of {MODEL_TYPES}, "
                         f"got {model_type!r}")
    return model_type


def resolve_max_windows() -> int:
    """SPARKNET_SERVE_MAX_WINDOWS: the fan-out width one compound
    request may demand (default 256).  An unbounded request would let a
    single client monopolize every bucket on the lane — this is the
    compound analogue of queue_depth."""
    raw = os.environ.get(MAX_WINDOWS_ENV, "256")
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{MAX_WINDOWS_ENV}={raw!r} is not an int")
    if v < 1:
        raise ValueError(f"{MAX_WINDOWS_ENV} must be >= 1, got {v}")
    return v


# ------------------------------------------------------------- ingress
def parse_windows(raw, *, source: str = "compound request"
                  ) -> List[Tuple[int, int, int, int]]:
    """Validate proposal windows arriving over the serving surface into
    [(x1, y1, x2, y2)] int tuples.  Same contract as every file-format
    parser in this repo (CLAUDE.md): malformed input dies with a
    ValueError naming `source`, never an IndexError/TypeError — a
    network ingress is just a parser whose file is a request."""
    if raw is None:
        raise ValueError(f"{source}: windows must be a non-empty list "
                         f"of [x1, y1, x2, y2], got null")
    try:
        entries = list(raw)
    except TypeError:
        raise ValueError(f"{source}: windows must be a list of "
                         f"[x1, y1, x2, y2], got {type(raw).__name__}")
    if not entries:
        raise ValueError(f"{source}: windows list is empty")
    cap = resolve_max_windows()
    if len(entries) > cap:
        raise ValueError(
            f"{source}: {len(entries)} windows exceeds the "
            f"{MAX_WINDOWS_ENV}={cap} per-request cap")
    out: List[Tuple[int, int, int, int]] = []
    for k, entry in enumerate(entries):
        try:
            vals = list(entry)
        except TypeError:
            raise ValueError(
                f"{source}: window {k} must be [x1, y1, x2, y2], got "
                f"{type(entry).__name__}")
        if len(vals) != 4:
            raise ValueError(
                f"{source}: window {k} has {len(vals)} coordinates, "
                f"expected 4 (x1, y1, x2, y2)")
        coords = []
        for v in vals:
            try:
                coords.append(int(v))
            except (TypeError, ValueError):
                raise ValueError(
                    f"{source}: window {k} coordinate {v!r} is not an "
                    f"integer")
        x1, y1, x2, y2 = coords
        if x2 < x1 or y2 < y1:
            raise ValueError(
                f"{source}: window {k} is inverted "
                f"(x1={x1}, y1={y1}, x2={x2}, y2={y2})")
        out.append((x1, y1, x2, y2))
    return out


# ---------------------------------------------------------- preprocess
def warp_windows(image_chw: np.ndarray,
                 windows: Sequence[Tuple[int, int, int, int]], *,
                 crop_size: int, context_pad: int = 0,
                 use_square: bool = False,
                 mean_values: Sequence[float] = (),
                 scale: float = 1.0,
                 source: str = "compound request") -> np.ndarray:
    """Crop + context-pad + warp every window of one (C, H, W) image to
    a (N, C, crop_size, crop_size) float32 batch — the offline
    WindowDataFeed._one pipeline (data/window_data.py) with mirroring
    off, op for op, so a served window's tensor is BITWISE the tensor
    the offline batch path builds for the same window (the parity pin
    in tests/test_serving_compound.py depends on this function staying
    in lockstep with _one)."""
    img = np.asarray(image_chw)
    if img.ndim != 3:
        raise ValueError(
            f"{source}: image must be (C, H, W), got shape "
            f"{tuple(img.shape)}")
    c, img_h, img_w = img.shape
    cs = int(crop_size)
    mv = list(mean_values)
    if len(mv) == 1 and c > 1:
        mv = mv * c
    if mv and len(mv) != c:
        raise ValueError(
            f"{source}: specify 1 mean_value or {c} (one per channel), "
            f"got {len(mv)}")
    mean = np.asarray(mv, dtype=np.float32) if mv else None
    out = np.zeros((len(windows), c, cs, cs), dtype=np.float32)
    for k, (wx1, wy1, wx2, wy2) in enumerate(windows):
        if context_pad <= 0 and not use_square:
            # the context-pad path clips to the image itself; the plain
            # path crops raw coordinates, so they must be in-bounds
            if not (0 <= wx1 and wx2 < img_w and 0 <= wy1
                    and wy2 < img_h):
                raise ValueError(
                    f"{source}: window {k} "
                    f"({wx1}, {wy1}, {wx2}, {wy2}) falls outside the "
                    f"{img_h}x{img_w} image")
        x1, y1, x2, y2, tw, th, pad_w, pad_h = expand_window(
            wx1, wy1, wx2, wy2, img_h, img_w, cs, int(context_pad),
            bool(use_square), False)
        roi = img[:, y1:y2 + 1, x1:x2 + 1]
        warped = _warp(roi, th, tw)
        region = warped
        if mean is not None:
            region = region - mean[:, None, None]
        out[k, :, pad_h:pad_h + th, pad_w:pad_w + tw] = \
            region * float(scale)
    return out


# ----------------------------------------------------------------- nms
def nms(boxes: np.ndarray, scores: np.ndarray,
        iou_threshold: float = 0.3) -> List[int]:
    """Greedy non-maximum suppression over inclusive-coordinate boxes
    (x1, y1, x2, y2); returns kept indices in descending-score order.
    Host-side numpy on the (small) per-image window set — the device
    answers raw per-window margins, suppression is assembly work."""
    b = np.asarray(boxes, dtype=np.float64)
    s = np.asarray(scores, dtype=np.float64)
    areas = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    order = np.argsort(-s, kind="stable")
    keep: List[int] = []
    while order.size:
        i = int(order[0])
        keep.append(i)
        rest = order[1:]
        ix1 = np.maximum(b[i, 0], b[rest, 0])
        iy1 = np.maximum(b[i, 1], b[rest, 1])
        ix2 = np.minimum(b[i, 2], b[rest, 2])
        iy2 = np.minimum(b[i, 3], b[rest, 3])
        iw = np.maximum(0.0, ix2 - ix1 + 1)
        ih = np.maximum(0.0, iy2 - iy1 + 1)
        inter = iw * ih
        iou = inter / (areas[i] + areas[rest] - inter)
        order = rest[iou <= iou_threshold]
    return keep


def nms_detections(windows: Sequence[Tuple[int, int, int, int]],
                   scores: np.ndarray, *, iou_threshold: float = 0.3,
                   score_min: float = 0.0) -> List[Dict[str, object]]:
    """Per-class greedy NMS over the (n_windows, n_classes) score
    matrix -> [{"window", "class", "score"}] sorted by descending
    score.  For rcnn_ilsvrc13 the scores are raw SVM margins (the
    deploy net ends at fc-rcnn, no softmax), so score_min=0.0 keeps
    exactly the positive-margin detections."""
    sc = np.asarray(scores)
    boxes = np.asarray(windows, dtype=np.float64)
    out: List[Dict[str, object]] = []
    for cls in range(sc.shape[1]):
        col = sc[:, cls]
        idx = np.nonzero(col > float(score_min))[0]
        if not idx.size:
            continue
        for k in nms(boxes[idx], col[idx], iou_threshold):
            w = idx[k]
            out.append({"window": tuple(int(v) for v in boxes[w]),
                        "class": int(cls),
                        "score": float(col[w])})
    out.sort(key=lambda d: -d["score"])
    return out


# ------------------------------------------------------------- fan-in
@dataclass
class CompoundResponse:
    """What a compound future resolves to: the per-window results of
    ONE image, reassembled in submission order from fragments that all
    carry the SAME generation (a reload landing mid-compound fails the
    compound rather than mixing params in one answer).

    `scores` is (n_windows, n_outputs): raw classifier margins for
    detect (plus the host-side `detections` NMS digest), the flattened
    capture_blob activations for featurize (alias `features`)."""

    model: str
    mode: str                      # "detect" | "featurize"
    scores: np.ndarray
    generation: int
    fragments: int
    buckets: List[int]             # distinct buckets the fragments rode
    queue_wait_ms: float           # max over fragments
    total_ms: float                # submit -> last fragment + assembly
    priority: str = "interactive"
    windows: Optional[List[Tuple[int, int, int, int]]] = None
    detections: Optional[List[Dict[str, object]]] = None

    @property
    def features(self) -> np.ndarray:
        return self.scores

    @property
    def argmaxes(self) -> np.ndarray:
        return np.argmax(self.scores, axis=1)


class CompoundAssembler:
    """Fan-in state for one compound request: collects fragment
    responses by index, resolves the compound future exactly once —
    with a full CompoundResponse when every fragment delivered from one
    generation, or with the FIRST fragment's rejection, after asking
    the server to discard the queued siblings (`cancel` callback; in-
    flight siblings complete and are ignored, their math is already
    launched).  Runs on batcher threads via future done-callbacks; the
    lock covers bookkeeping only — assembly, NMS, and the cancel sweep
    all run outside it."""

    def __init__(self, *, model: str, mode: str, n: int,
                 priority: str, t_submit: float,
                 windows: Optional[List[Tuple[int, int, int, int]]],
                 nms_iou: float, score_min: float,
                 cancel: Callable[["CompoundAssembler", Exception], int],
                 event: Callable[..., None]) -> None:
        self.future: Future = Future()
        self.model = model
        self.mode = mode
        self.n = int(n)
        self.priority = priority
        self.windows = windows
        self._t_submit = float(t_submit)
        self._nms_iou = float(nms_iou)
        self._score_min = float(score_min)
        self._cancel = cancel
        self._event = event
        self._mu = threading.Lock()
        self._results: List[Optional[object]] = [None] * self.n
        self._remaining = self.n
        self._sealed = False

    def _seal(self) -> bool:
        """Exactly-once gate on resolving the compound future: the
        first sealer (a fragment rejection, an external abort from the
        fan-out loop, or the final-fragment assembly) owns it; everyone
        else backs off.  Late sibling callbacks after a seal are the
        in-flight fragments completing — ignored by design."""
        with self._mu:
            if self._sealed:
                return False
            self._sealed = True
            return True

    def fragment_done(self, index: int, fut: Future) -> None:
        """Done-callback for fragment `index`'s future."""
        exc = fut.exception()
        if exc is not None:
            self.abort(exc)
            return
        result = fut.result()   # resolved: we run from add_done_callback
        with self._mu:
            if self._sealed:
                return          # compound already aborted; late sibling
            self._results[index] = result
            self._remaining -= 1
            if self._remaining:
                return
        self._assemble()

    def abort(self, exc: Exception) -> bool:
        """Fail the compound with `exc` (first caller wins): discard
        the queued sibling fragments, log, resolve the compound future
        with the rejection.  Returns whether this call was the one that
        sealed."""
        if not self._seal():
            return False
        self._fail(exc)
        return True

    def _fail(self, exc: Exception) -> None:
        discarded = self._cancel(self, exc)
        self._event("compound_abort", model=self.model, mode=self.mode,
                    fragments=self.n, discarded=discarded,
                    priority=self.priority,
                    error=type(exc).__name__)
        self.future.set_exception(exc)

    def _assemble(self) -> None:
        if not self._seal():
            return
        gens = {r.generation for r in self._results}
        if len(gens) != 1:
            # a reload swapped params mid-compound: the fragments are
            # individually correct but belong to DIFFERENT models — a
            # mixed answer is exactly the partial response the
            # all-or-nothing contract forbids
            from .errors import ServingError

            self._fail(ServingError(
                f"compound to {self.model!r} spans generations "
                f"{sorted(gens)}; all-or-nothing assembly refuses to "
                f"mix them"))
            return
        scores = np.stack([r.probs for r in self._results])
        buckets = sorted({r.bucket for r in self._results})
        queue_wait = max(r.queue_wait_ms for r in self._results)
        total_ms = (now_s() - self._t_submit) * 1e3
        detections = None
        if self.mode == "detect" and self.windows is not None:
            detections = nms_detections(
                self.windows, scores, iou_threshold=self._nms_iou,
                score_min=self._score_min)
        resp = CompoundResponse(
            model=self.model, mode=self.mode, scores=scores,
            generation=gens.pop(), fragments=self.n, buckets=buckets,
            queue_wait_ms=round(queue_wait, 4),
            total_ms=round(total_ms, 4), priority=self.priority,
            windows=self.windows, detections=detections)
        self._event("compound_assembled", model=self.model,
                    mode=self.mode, fragments=self.n, buckets=buckets,
                    priority=self.priority,
                    detections=(len(detections)
                                if detections is not None else None),
                    total_ms=round(total_ms, 4))
        self.future.set_result(resp)


# -------------------------------------------------------------- events
class CompoundEventLog:
    """Compound lifecycle events: an in-memory list (tests/drill
    observability) plus an optional JSONL sink
    (SPARKNET_SERVE_COMPOUND_LOG).  Events are wall-clock-free — kinds
    and counts only, durations in ms — matching the resilience event
    discipline (DISTACC.md)."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = (path if path is not None
                     else os.environ.get(COMPOUND_LOG_ENV) or None)
        self.events: List[dict] = []
        self._mu = threading.Lock()

    def __call__(self, kind: str, **fields) -> None:
        ev = {"kind": kind}
        ev.update(fields)
        with self._mu:
            self.events.append(ev)
            if self.path:
                try:
                    with open(self.path, "a") as f:
                        f.write(json.dumps(ev) + "\n")
                except OSError:
                    self.path = None    # never let a dead disk serve 500s

    def snapshot(self) -> List[dict]:
        with self._mu:
            return [dict(e) for e in self.events]

    def counts(self) -> Dict[str, int]:
        with self._mu:
            out: Dict[str, int] = {}
            for e in self.events:
                out[e["kind"]] = out.get(e["kind"], 0) + 1
            return out
