"""Worker entrypoint for the fleet serving router (serving/fleet.py).

One OS process = one full inference stack: the worker owns a complete
`InferenceServer` (engine + registry + scheduler + stats) serving ONE
model on its own device slice — the SparkNet worker shape (full model
replica per executor process) applied to serving instead of training,
and the process-granularity answer to the GIL bound PR 8 measured on
in-process replicas.

Protocol (router -> stdin / stdout -> router):

  ready     one text JSON line after load+warmup:
            {"ready": true, "worker": N, "pid": ..., "model": ...,
             "generation": g, "sample_shape": [...], "buckets": [...],
             "n_outputs": k, "compiles": c, "quant": ..., "shards": s}
  frames    after the ready line BOTH pipes switch to elastic/ipc.py
            binary frames (magic+length+npz).  Commands:
              {"cmd": "infer", "seq": s, "count": k,
               "priorities": [...]}            + array "x" (k, *shape)
              {"cmd": "reload", "seq": s}
              {"cmd": "probe", "seq": s}
              {"cmd": "stats", "seq": s}
              {"cmd": "stop", "seq": s}
            Every command gets exactly one reply frame echoing "seq".
            An infer reply carries per-request parallel lists
            (statuses/generations/buckets/batch_live/device_ms) plus
            the "probs" array — failed rows hold a status dict and a
            zero row, so one poisoned request never fails its batch.

The worker NEVER writes to stdout outside the ready line + reply frames
(the router's reader thread owns the pipe).  Heartbeats are file-mtime
touches every `heartbeat_s` from a daemon thread (ipc.Heartbeat); they
stall exactly while the process is SIGSTOP'd or dead, which is what the
router's watchdog measures.  stdin EOF means the router is gone: drain
and exit.  `generation_base` in the config makes a respawned worker
report the fleet-wide generation (base + local reload count), so a
process that missed earlier reload() cycles still stamps responses
consistently.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu() -> None:
    # the box's sitecustomize pre-imports jax, so the live-config update
    # is what actually takes effect (tests/conftest.py pattern)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")


def _status_of(exc) -> dict:
    from .errors import ServingError

    if isinstance(exc, ServingError):
        return {"error": type(exc).__name__, "status": exc.status,
                "detail": str(exc)}
    return {"error": type(exc).__name__, "status": 500,
            "detail": str(exc)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleet_worker")
    ap.add_argument("--config", required=True,
                    help="worker config JSON written by the router")
    a = ap.parse_args(argv)
    with open(a.config) as f:
        cfg = json.load(f)
    if cfg.get("force_cpu", True):
        _force_cpu()

    import numpy as np

    from ..elastic import ipc
    from .server import InferenceServer, ServerConfig

    slot = int(cfg["worker"])
    name = str(cfg["model"])
    gen_base = int(cfg.get("generation_base", 0))
    result_timeout_s = float(cfg.get("result_timeout_s", 120.0))

    beat = None
    if cfg.get("heartbeat_path"):
        beat = ipc.Heartbeat(cfg["heartbeat_path"],
                             float(cfg.get("heartbeat_s", 0.25)))

    max_batch = int(cfg.get("max_batch", 8))
    scfg = ServerConfig(
        max_batch=max_batch,
        max_wait_ms=float(cfg.get("max_wait_ms", 0.0)),
        # the inner queue must absorb a full router batch without
        # blocking the command loop's submit fan-out
        queue_depth=max(int(cfg.get("queue_depth", 64)), 2 * max_batch),
        default_deadline_ms=None,
        min_fill=1)
    server = InferenceServer(scfg)
    lm = server.load(
        name, cfg.get("spec"),
        weights=cfg.get("weights"),
        buckets=cfg.get("buckets"),
        seed=int(cfg.get("seed", 0)),
        quant=cfg.get("quant", "fp32"),
        quant_min_agreement=cfg.get("quant_min_agreement"),
        replicas=1,
        shards=cfg.get("shards"))
    n_out = int(lm.runner.n_outputs)
    sample_shape = tuple(lm.runner.sample_shape)

    out = sys.stdout.buffer
    out.write((json.dumps(
        {"ready": True, "worker": slot, "pid": os.getpid(),
         "model": name, "generation": gen_base + int(lm.generation),
         "sample_shape": list(sample_shape),
         "buckets": list(lm.runner.buckets),
         "n_outputs": n_out,
         "compiles": int(lm.runner.compile_count()),
         "quant": lm.runner.quant,
         "shards": int(lm.runner.shards)}) + "\n").encode("utf-8"))
    out.flush()

    stdin = sys.stdin.buffer
    tag = f"fleet_worker[{slot}] stdin"

    def reply(meta, arrays=None):
        ipc.write_frame(out, meta, arrays)

    try:
        while True:
            try:
                frame = ipc.read_frame(stdin, what=tag)
            except ipc.IpcClosed:
                break
            if frame is None:       # router gone: drain and exit
                break
            meta, arrays = frame
            cmd = meta.get("cmd")
            seq = meta.get("seq")
            if cmd == "stop":
                reply({"cmd": "stopped", "seq": seq, "ok": True})
                break
            if cmd == "infer":
                x = arrays["x"]
                k = int(meta.get("count", x.shape[0]))
                pris = meta.get("priorities") or ["interactive"] * k
                futs = []
                for j in range(k):
                    try:
                        futs.append(server.submit(
                            name, np.asarray(x[j]), wait=True,
                            priority=pris[j]))
                    except Exception as e:
                        futs.append(e)
                statuses, gens, buckets, lives, dms = [], [], [], [], []
                probs = np.zeros((k, n_out), dtype=np.float32)
                for j, fut in enumerate(futs):
                    r = None
                    if isinstance(fut, Exception):
                        statuses.append(_status_of(fut))
                    else:
                        try:
                            r = fut.result(timeout=result_timeout_s)
                        except Exception as e:
                            statuses.append(_status_of(e))
                    if r is None:
                        gens.append(-1)
                        buckets.append(0)
                        lives.append(0)
                        dms.append(0.0)
                        continue
                    statuses.append(None)
                    probs[j] = np.asarray(r.probs, dtype=np.float32)
                    gens.append(gen_base + int(r.generation))
                    buckets.append(int(r.bucket))
                    lives.append(int(r.batch_live))
                    dms.append(float(r.device_ms))
                reply({"cmd": "result", "seq": seq, "ok": True,
                       "count": k, "statuses": statuses,
                       "generations": gens, "buckets": buckets,
                       "batch_live": lives, "device_ms": dms},
                      {"probs": probs})
            elif cmd == "reload":
                try:
                    new_lm = server.reload(name)
                    reply({"cmd": "reloaded", "seq": seq, "ok": True,
                           "generation":
                               gen_base + int(new_lm.generation),
                           "compiles":
                               int(new_lm.runner.compile_count())})
                except Exception as e:
                    reply({"cmd": "reloaded", "seq": seq, "ok": False,
                           **_status_of(e)})
            elif cmd == "probe":
                # end-to-end health probe: a real request through the
                # full inner stack, not just a device ping
                try:
                    fut = server.submit(
                        name, np.zeros(sample_shape, dtype=np.float32),
                        wait=True)
                    fut.result(timeout=result_timeout_s)
                    reply({"cmd": "probed", "seq": seq, "ok": True})
                except Exception as e:
                    reply({"cmd": "probed", "seq": seq, "ok": False,
                           **_status_of(e)})
            elif cmd == "stats":
                try:
                    payload = json.loads(
                        json.dumps(server.stats(), default=str))
                    reply({"cmd": "stats", "seq": seq, "ok": True,
                           "stats": payload})
                except Exception as e:
                    reply({"cmd": "stats", "seq": seq, "ok": False,
                           **_status_of(e)})
            else:
                reply({"cmd": "error", "seq": seq, "ok": False,
                       "error": "UnknownCommand", "status": 400,
                       "detail": f"unknown fleet command {cmd!r}"})
    except ipc.IpcClosed:
        pass                        # router hung up mid-reply: just exit
    finally:
        server.close(drain=True)
        if beat is not None:
            beat.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
