"""SLO-driven serving autoscaler: grow and shrink a model's replica
set through the placement/scheduler/registry levers the circuit
breakers already exercise.

PR 15 gave the serving tier reflexes (resilience.py: a TRIPPED replica
is evicted, respawned, probed back in) and PR 17 gave it big-model
slices (one replica = an N-device gspmd shard).  This module closes
the control loop the other way: CAPACITY itself becomes a controlled
variable.  A per-model daemon samples the sensors the tier already
maintains — lane queue fraction, the interactive total-latency EWMA
the shed controller's SLO is defined over, open-breaker count — and
walks the replica set up and down through the SAME primitives the
breaker uses, so scaling inherits the exactly-once story wholesale:

- **Slot pool, not dynamic arrays.**  `load(name, replicas=POOL)`
  builds and warms every slot once; the autoscaler manages an
  active/PARKED partition of the pool.  Parking a slot is a controlled
  drain-and-evict (scheduler `disable_unless_last` -> atomic
  `drain_replica` -> exactly-once `requeue(exclude=victim)` ->
  `DevicePlacer.evict`): admitted requests are rerouted, never dropped
  or re-answered.  Un-parking respawns the slot onto the currently
  LEAST-LOADED device — `DevicePlacer.respawn(rebind=True)` — then
  `ModelRegistry.rebuild_replica(device=...)` builds a fresh warmed
  runner there (same params, no generation bump) before routing
  re-opens.  With `shards=N` (PR 17) the unit is a mesh slice; the
  slot algebra is identical.
- **Hysteresis, not a thermostat.**  `ScalePolicy` is a pure
  tick-indexed state machine: overload (queue fraction >= up_q OR
  EWMA > SLO) must persist `up_ticks` consecutive ticks to scale up,
  idle (queue fraction <= down_q) must persist `down_ticks` to scale
  down, and every action opens a `cooldown_ticks` refractory window.
  No wall clock enters any decision, so `ScalePolicy.replay` over a
  seeded sensor trace is bitwise-reproducible (`schedule_digest` pins
  it — the same determinism-over-the-schedule contract as
  ServeFaultPlan, since live thread interleavings naturally vary).
- **Composes with the breakers, never competes.**  (1) Scale-up is
  SUPPRESSED while any breaker is open: an errstorm raises latency,
  and adding replicas to an error-dominated lane is a doom loop —
  recovery is the breaker's job (the drill pins trips >= 1 with ZERO
  scale-ups).  (2) A parked slot is invisible to breaker accounting:
  the manager's activity gate (`set_activity_gate`) drops outcome
  records from in-flight stragglers, so a parked slot's breaker stays
  closed and can never double-evict residency the autoscaler already
  released.  (3) A non-closed slot is never a scale victim or scale-up
  candidate, and a lost `placer.evict` race (the breaker got there
  first) aborts the park — the slot stays the breaker's.
- **Floors are hard.**  `min_replicas >= 1` always; the scheduler's
  atomic `disable_unless_last` backstops the n=1 case so no
  interleaving of breaker and autoscaler can zero a lane's capacity.

Every transition lands as a wall-clock-free JSONL event (`scale_init`
/ `scale_up` / `scale_down` / `scale_suppressed` / `scale_error`;
schema in DISTACC.md), and the sensors export as named gauges
(`serving_queue_fraction`, `serving_interactive_ewma_ms`,
`serving_active_replicas`) in the model's metrics registry — the
autoscaler, the shed controller, and a Prometheus scrape all read one
set of numbers.  Drill: `scripts/autoscale_drill.py` (shaped load:
diurnal / spike / flash-crowd / errstorm); bench leg:
`serving_autoscale`.

Locking: `_mu` guards policy state, the parked set, and counters, and
is NEVER held across a scheduler/placer/registry/stats/resilience
call or a sleep (ANALYSIS.md R008); the activity gate takes `_mu`
alone and is called by the manager BEFORE its own `_mu`, so the lock
graph stays acyclic (R007).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..elastic.chaos import u01
from .errors import ServerClosed
from .resilience import SLO_ENV, _devstr, _env_float, _env_int
from .scheduler import SchedulerClosed

__all__ = [
    "AutoscaleConfig", "Autoscaler", "ScalePolicy", "SensorSample",
    "synthetic_sensor_trace", "LOAD_SHAPES",
    "SCALE_MIN_ENV", "SCALE_UP_Q_ENV", "SCALE_DOWN_Q_ENV",
    "SCALE_UP_TICKS_ENV", "SCALE_DOWN_TICKS_ENV", "SCALE_COOLDOWN_ENV",
]

SCALE_MIN_ENV = "SPARKNET_SERVE_SCALE_MIN"
SCALE_UP_Q_ENV = "SPARKNET_SERVE_SCALE_UP_Q"
SCALE_DOWN_Q_ENV = "SPARKNET_SERVE_SCALE_DOWN_Q"
SCALE_UP_TICKS_ENV = "SPARKNET_SERVE_SCALE_UP_TICKS"
SCALE_DOWN_TICKS_ENV = "SPARKNET_SERVE_SCALE_DOWN_TICKS"
SCALE_COOLDOWN_ENV = "SPARKNET_SERVE_SCALE_COOLDOWN_TICKS"

LOAD_SHAPES = ("diurnal", "spike", "flash_crowd", "errstorm")


# ------------------------------------------------------------------ sensors
@dataclasses.dataclass(frozen=True)
class SensorSample:
    """One tick's sensor reading — everything a scaling decision may
    depend on, and nothing else (no wall clock, no thread state), so a
    recorded trace replays the policy bitwise."""

    queue_fraction: float               # lane queued / queue_depth
    interactive_ewma_ms: Optional[float]   # shed controller's SLO EWMA
    breakers_open: int                  # non-closed breakers right now


# ------------------------------------------------------------------- config
@dataclasses.dataclass
class AutoscaleConfig:
    """Knobs of the autoscaling policy.  Every default reads its scale
    env knob — SPARKNET_SERVE_SCALE_MIN and friends, registered in
    analysis/knobs.py + the README table (R004) — so deployments tune
    without code; explicit constructor values win.  Thresholds are in TICKS of the policy
    clock (`tick_s`), not seconds — the policy itself never sees wall
    time, which is what makes `ScalePolicy.replay` exact."""

    min_replicas: int = dataclasses.field(
        default_factory=lambda: _env_int(SCALE_MIN_ENV, 1))
    initial_replicas: Optional[int] = None   # None -> min_replicas
    up_queue_fraction: float = dataclasses.field(
        default_factory=lambda: _env_float(SCALE_UP_Q_ENV, 0.5))
    down_queue_fraction: float = dataclasses.field(
        default_factory=lambda: _env_float(SCALE_DOWN_Q_ENV, 0.125))
    up_ticks: int = dataclasses.field(
        default_factory=lambda: _env_int(SCALE_UP_TICKS_ENV, 2))
    down_ticks: int = dataclasses.field(
        default_factory=lambda: _env_int(SCALE_DOWN_TICKS_ENV, 6))
    cooldown_ticks: int = dataclasses.field(
        default_factory=lambda: _env_int(SCALE_COOLDOWN_ENV, 8))
    slo_ms: float = dataclasses.field(
        default_factory=lambda: _env_float(SLO_ENV, 500.0))
    tick_s: float = 0.05        # daemon sampling period
    event_log: Optional[str] = None   # JSONL path (DISTACC.md schema)

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {self.min_replicas}")
        if (self.initial_replicas is not None
                and self.initial_replicas < self.min_replicas):
            raise ValueError(
                f"initial_replicas must be >= min_replicas="
                f"{self.min_replicas}, got {self.initial_replicas}")
        if not 0.0 < self.up_queue_fraction <= 1.0:
            raise ValueError(f"up_queue_fraction must be in (0, 1], "
                             f"got {self.up_queue_fraction}")
        if not 0.0 <= self.down_queue_fraction < self.up_queue_fraction:
            raise ValueError(
                f"down_queue_fraction must be in [0, "
                f"up_queue_fraction={self.up_queue_fraction}), got "
                f"{self.down_queue_fraction}")
        if self.up_ticks < 1:
            raise ValueError(f"up_ticks must be >= 1, "
                             f"got {self.up_ticks}")
        if self.down_ticks < 1:
            raise ValueError(f"down_ticks must be >= 1, "
                             f"got {self.down_ticks}")
        if self.cooldown_ticks < 0:
            raise ValueError(f"cooldown_ticks must be >= 0, "
                             f"got {self.cooldown_ticks}")
        if self.slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {self.slo_ms}")
        if self.tick_s <= 0:
            raise ValueError(f"tick_s must be > 0, got {self.tick_s}")

    @property
    def floor(self) -> int:
        """The hard capacity floor: never below one replica, whatever
        min_replicas says."""
        return max(1, self.min_replicas)


# ------------------------------------------------------------------- policy
class ScalePolicy:
    """Pure hysteresis/cooldown state machine over tick indices.

    `decide()` consumes one SensorSample and returns
    `(action, suppressed)` with action in {"up", "down", "hold"}.
    Overload = queue fraction >= up_queue_fraction OR interactive EWMA
    over the SLO; it must persist `up_ticks` consecutive ticks before
    an "up" fires.  Idle = queue fraction <= down_queue_fraction,
    persisting `down_ticks` before a "down".  Any fired action opens a
    `cooldown_ticks` refractory window during which everything holds
    (streaks keep accumulating, so a still-overloaded lane fires again
    the tick the window closes).  Overload while ANY breaker is open
    is MASKED (suppressed=True, action "hold"): an errstorm's latency
    spike must trip breakers, never a scale-up doom loop.

    Deliberately free of wall clock, RNG, and thread state: the same
    sample sequence always yields the same action schedule, which is
    the drill's bitwise replay contract (`replay` / `schedule_digest`,
    mirroring ServeFaultPlan's determinism-over-the-schedule)."""

    def __init__(self, cfg: AutoscaleConfig) -> None:
        self.cfg = cfg
        self.tick = 0
        self.up_streak = 0
        self.down_streak = 0
        self.cooldown = 0

    def decide(self, sample: SensorSample, *, active: int,
               pool: int) -> Tuple[str, bool]:
        cfg = self.cfg
        self.tick += 1
        overload = (sample.queue_fraction >= cfg.up_queue_fraction
                    or (sample.interactive_ewma_ms is not None
                        and sample.interactive_ewma_ms > cfg.slo_ms))
        suppressed = False
        if overload and sample.breakers_open > 0:
            overload = False
            suppressed = True
        idle = (not overload and not suppressed
                and sample.queue_fraction <= cfg.down_queue_fraction)
        if overload:
            self.up_streak += 1
            self.down_streak = 0
        elif idle:
            self.down_streak += 1
            self.up_streak = 0
        else:
            self.up_streak = 0
            self.down_streak = 0
        if self.cooldown > 0:
            self.cooldown -= 1
            return "hold", suppressed
        if self.up_streak >= cfg.up_ticks and active < pool:
            self.up_streak = self.down_streak = 0
            self.cooldown = cfg.cooldown_ticks
            return "up", suppressed
        if self.down_streak >= cfg.down_ticks and active > cfg.floor:
            self.up_streak = self.down_streak = 0
            self.cooldown = cfg.cooldown_ticks
            return "down", suppressed
        return "hold", suppressed

    # ------------------------------------------------------------- replay
    @classmethod
    def replay(cls, cfg: AutoscaleConfig, samples: Sequence[SensorSample],
               *, initial_active: int,
               pool: int) -> List[Tuple[int, str, bool, int]]:
        """Run a fresh policy over `samples` and return the full
        schedule [(tick, action, suppressed, active_after)].  Pure: two
        calls with the same inputs agree bitwise on every entry."""
        pol = cls(cfg)
        active = int(initial_active)
        out: List[Tuple[int, str, bool, int]] = []
        for s in samples:
            action, suppressed = pol.decide(s, active=active, pool=pool)
            if action == "up":
                active += 1
            elif action == "down":
                active -= 1
            out.append((pol.tick, action, suppressed, active))
        return out

    @classmethod
    def schedule_digest(cls, cfg: AutoscaleConfig,
                        samples: Sequence[SensorSample], *,
                        initial_active: int, pool: int) -> str:
        """sha256 over the full replayed schedule — the drill computes
        it twice from independently constructed traces and pins
        equality (the bitwise two-run replay contract)."""
        h = hashlib.sha256()
        for tick, action, suppressed, active in cls.replay(
                cfg, samples, initial_active=initial_active, pool=pool):
            h.update(f"{tick}:{action}:{int(suppressed)}:{active}|"
                     .encode())
        return h.hexdigest()


def synthetic_sensor_trace(shape: str, *, seed: int = 0,
                           n_ticks: int = 240,
                           slo_ms: float = 500.0
                           ) -> List[SensorSample]:
    """A seeded, shaped sensor trace — pure function of
    (shape, seed, n_ticks, slo_ms), every draw via the sha256 `u01`
    elastic/chaos.py uses, so two constructions agree bitwise (the
    replay-digest half of the drill).  Shapes mirror
    scripts/serve_loadgen.py's load shapes:

      diurnal      sinusoidal day/night swing (grow at peak, shrink at
                   trough)
      spike        quiet -> sudden 20%-of-trace plateau -> quiet
      flash_crowd  quiet -> permanent step up
      errstorm     saturated AND breakers open — the doom-loop case;
                   a correct policy emits zero "up" actions here
    """
    if shape not in LOAD_SHAPES:
        raise ValueError(f"unknown load shape {shape!r}; one of "
                         f"{LOAD_SHAPES}")
    if n_ticks < 1:
        raise ValueError(f"n_ticks must be >= 1, got {n_ticks}")
    out: List[SensorSample] = []
    for t in range(int(n_ticks)):
        p = t / max(1, int(n_ticks) - 1)
        if shape == "diurnal":
            m = 1.0 + 0.6 * math.sin(2 * math.pi * p)
        elif shape == "spike":
            m = 1.8 if 0.4 <= p < 0.6 else 0.3
        elif shape == "flash_crowd":
            m = 0.2 if p < 0.3 else 1.8
        else:                                  # errstorm
            m = 1.8
        jitter = 0.05 * (u01(int(seed), "scale_trace", t) - 0.5)
        qf = max(0.0, min(1.0, 0.55 * m - 0.15 + jitter))
        ewma = float(slo_ms) * (0.3 + 0.45 * m)
        # errstorm: errors dominate from the first dispatch, so the
        # breaker is open before queue pressure can persist — the whole
        # trace must yield ZERO "up" actions (the doom-loop pin)
        breakers = 1 if shape == "errstorm" else 0
        out.append(SensorSample(queue_fraction=round(qf, 6),
                                interactive_ewma_ms=round(ewma, 3),
                                breakers_open=breakers))
    return out


# --------------------------------------------------------------- autoscaler
class Autoscaler:
    """Per-lane scaling daemon over a fixed warmed slot pool.

    Wiring (serving/server.py): built after the lane's scheduler and
    ResilienceManager, with the pool fully placed; the constructor
    immediately PARKS every slot above `initial_replicas` (disable ->
    drain -> evict, releasing device residency back to the placer) and
    registers its `is_active` as the manager's activity gate.  The
    daemon then samples each `tick_s`: queue fraction from the
    scheduler, the interactive EWMA + open-breaker count from the
    manager, feeds `ScalePolicy`, and applies at most one scaling
    action per tick through the placer/registry/scheduler — always
    with `_mu` released (R008)."""

    def __init__(self, *, model: str, sched, lm, registry, placer,
                 queue_depth: int, resil=None,
                 config: Optional[AutoscaleConfig] = None) -> None:
        self.cfg = config if config is not None else AutoscaleConfig()
        self._model = str(model)
        self._sched = sched
        self._lm = lm
        self._registry = registry
        self._placer = placer
        self._resil = resil
        self._queue_depth = int(queue_depth)
        self._pool = int(lm.n_replicas)
        if self.cfg.floor > self._pool:
            raise ValueError(
                f"min_replicas={self.cfg.min_replicas} exceeds the "
                f"{self._pool}-slot pool for model {model!r}")
        initial = (self.cfg.initial_replicas
                   if self.cfg.initial_replicas is not None
                   else self.cfg.floor)
        initial = max(self.cfg.floor, min(int(initial), self._pool))
        self._mu = threading.Lock()
        self._ev_mu = threading.Lock()   # serializes event-log appends
        self._policy = ScalePolicy(self.cfg)
        self._parked: set = set()
        self._ups = 0
        self._downs = 0
        self._suppressed = 0            # suppressed ticks
        self._blocked_up = 0
        self._blocked_down = 0
        self._errors = 0
        self._in_suppress_episode = False
        self._min_active = initial
        self._max_active = initial
        self.events: List[dict] = []
        # park the tail of the pool BEFORE any traffic: the slots were
        # built and warmed by load() (scale-up is a rebind+rebuild, not
        # a cold compile), but they start without device residency or
        # routing.  The gate is registered first so a parked slot is
        # never breaker-visible, even transiently.
        if self._resil is not None:
            self._resil.set_activity_gate(self.is_active)
        for slot in range(self._pool - 1, initial - 1, -1):
            with self._mu:
                self._parked.add(slot)
            self._sched.set_enabled(slot, False)
            drained = self._sched.drain_replica(slot)
            if drained:
                self._sched.requeue(drained, exclude=slot)
            if self._placer is not None:
                try:
                    self._placer.evict(self._model, slot)
                except ValueError:
                    pass        # no recorded placement for this slot
        self._event("scale_init", active=initial, pool=self._pool,
                    floor=self.cfg.floor,
                    parked=sorted(self._parked))
        self._push_active_gauge()
        self._stop_ev = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"sparknet-scale-{model}",
            daemon=True)
        self._thread.start()

    # ---------------------------------------------------------------- gate
    def is_active(self, replica: int) -> bool:
        """True while `replica` is un-parked — the ResilienceManager's
        activity gate (outcomes from parked slots are dropped so their
        breakers stay closed).  Takes `_mu` alone; callers never hold
        their own locks across it (R007)."""
        with self._mu:
            return int(replica) not in self._parked

    def active_count(self) -> int:
        with self._mu:
            return self._pool - len(self._parked)

    # ---------------------------------------------------------------- loop
    def _loop(self) -> None:
        while not self._stop_ev.wait(self.cfg.tick_s):
            try:
                self.step()
            except Exception as e:      # keep the control plane alive
                with self._mu:
                    self._errors += 1
                self._event("scale_error",
                            error=f"{type(e).__name__}: {e}")

    def step(self) -> None:
        """One sensing + decision + (at most one) action cycle.  Public
        so tests and the drill can drive the policy synchronously with
        the daemon stopped."""
        sample = self._sense()
        with self._mu:
            active = self._pool - len(self._parked)
            action, suppressed = self._policy.decide(
                sample, active=active, pool=self._pool)
            tick = self._policy.tick
            if suppressed:
                self._suppressed += 1
            first_suppress = suppressed and not self._in_suppress_episode
            self._in_suppress_episode = suppressed
        if first_suppress:
            self._event("scale_suppressed", tick=tick,
                        breakers_open=sample.breakers_open,
                        queue_fraction=round(sample.queue_fraction, 4))
        if action == "up":
            self._scale_up(tick, sample)
        elif action == "down":
            self._scale_down(tick, sample)

    def _sense(self) -> SensorSample:
        qf = self._sched.queued_total() / float(self._queue_depth)
        ewma = (self._resil.interactive_ewma()
                if self._resil is not None else None)
        open_n = (self._resil.open_breakers()
                  if self._resil is not None else 0)
        self._lm.stats.observe_sensors(queue_fraction=qf)
        return SensorSample(queue_fraction=qf,
                            interactive_ewma_ms=ewma,
                            breakers_open=open_n)

    def _push_active_gauge(self) -> None:
        with self._mu:
            active = self._pool - len(self._parked)
        self._lm.stats.observe_sensors(active_replicas=active)

    # ------------------------------------------------------------- scale up
    def _scale_up(self, tick: int, sample: SensorSample) -> None:
        """Un-park the lowest eligible slot: respawn onto the currently
        least-loaded device/slice (rebind), rebuild a fresh warmed
        runner there, and only then re-open routing — the slot's first
        live dispatch hits warm compiled buckets on its new home."""
        with self._mu:
            parked = sorted(self._parked)
        slot = None
        for cand in parked:     # a non-closed slot is the breaker's
            if (self._resil is None
                    or self._resil.breaker_state(cand) == "closed"):
                slot = cand
                break
        if slot is None:
            with self._mu:
                self._blocked_up += 1
            return
        device = None
        if self._placer is not None:
            try:
                device = self._placer.respawn(self._model, slot,
                                              rebind=True)
            except ValueError:
                device = None   # slot never had a recorded placement
        try:
            self._registry.rebuild_replica(self._model, slot,
                                           device=device)
        except Exception as e:
            # give the residency back; the slot stays parked
            if device is not None and self._placer is not None:
                try:
                    self._placer.evict(self._model, slot)
                except ValueError:
                    pass
            with self._mu:
                self._errors += 1
            self._event("scale_error", tick=tick, replica=slot,
                        error=f"scale-up rebuild failed: "
                              f"{type(e).__name__}: {e}")
            return
        with self._mu:
            self._parked.discard(slot)
            self._ups += 1
            active = self._pool - len(self._parked)
            self._max_active = max(self._max_active, active)
        # un-parked BEFORE routing opens: the first dispatch outcome
        # must already pass the activity gate
        self._sched.set_enabled(slot, True)
        # breakers_open rides along as an audit field: decide() masks
        # overload while any breaker is open, so a scale_up event with
        # breakers_open > 0 is impossible by construction — the drill
        # pins exactly that (the doom-loop invariant)
        self._event("scale_up", tick=tick, replica=slot,
                    device=_devstr(device), active=active,
                    queue_fraction=round(sample.queue_fraction, 4),
                    breakers_open=sample.breakers_open)
        self._push_active_gauge()

    # ----------------------------------------------------------- scale down
    def _scale_down(self, tick: int, sample: SensorSample) -> None:
        """Park the highest eligible slot: atomically close routing
        (never the last enabled replica), drain its queue, requeue the
        drained items exactly once onto the survivors, release device
        residency.  Slot 0 (the registry master) is only ever parked if
        it is somehow the last candidate above the floor — victim order
        is highest-index-first precisely to keep it resident."""
        with self._mu:
            active = sorted(
                (s for s in range(self._pool) if s not in self._parked),
                reverse=True)
        if len(active) <= self.cfg.floor:
            with self._mu:
                self._blocked_down += 1
            return
        victim = None
        for cand in active:     # a non-closed slot is the breaker's
            if (self._resil is None
                    or self._resil.breaker_state(cand) == "closed"):
                victim = cand
                break
        if victim is None:
            with self._mu:
                self._blocked_down += 1
            return
        # capacity floor over ROUTED replicas too: breakers may have
        # disabled other active slots, and parking below the floor of
        # live routing capacity would amplify their outage
        if self._sched.enabled_count() - 1 < self.cfg.floor:
            with self._mu:
                self._blocked_down += 1
            return
        if not self._sched.disable_unless_last(victim):
            with self._mu:
                self._blocked_down += 1
            return
        # parked BEFORE the drain: any in-flight straggler outcome on
        # the victim is already gate-invisible to its breaker
        with self._mu:
            self._parked.add(victim)
        drained = self._sched.drain_replica(victim)
        if drained:
            try:
                self._sched.requeue(drained, exclude=victim)
            except SchedulerClosed:
                # shutdown race (the server stops the autoscaler first,
                # so this is a backstop, not a path): reject loudly —
                # an admitted request is never silently dropped
                for r in drained:
                    fut = getattr(r, "future", None)
                    if fut is not None:
                        fut.set_exception(ServerClosed(
                            "server closed while rebalancing this "
                            "request off a scaled-down replica"))
        evicted_device = None
        if self._placer is not None:
            try:
                evicted_device = self._placer.evict(self._model, victim)
            except ValueError:
                # the breaker tripped concurrently and evicted first:
                # the slot is the BREAKER's episode now — un-park so
                # its respawn/close path re-admits it normally, and
                # count nothing (no double bookkeeping)
                with self._mu:
                    self._parked.discard(victim)
                    self._errors += 1
                self._event("scale_error", tick=tick, replica=victim,
                            error="scale-down lost evict race to "
                                  "breaker; slot left to resilience")
                return
        with self._mu:
            self._downs += 1
            active_n = self._pool - len(self._parked)
            self._min_active = min(self._min_active, active_n)
        self._event("scale_down", tick=tick, replica=victim,
                    requeued=len(drained), device=_devstr(evicted_device),
                    active=active_n,
                    queue_fraction=round(sample.queue_fraction, 4))
        self._push_active_gauge()

    # -------------------------------------------------------------- observe
    def snapshot(self) -> Dict[str, object]:
        """JSON-ready autoscaler state for server.stats() and the
        drill's accounting checks."""
        with self._mu:
            return {
                "pool": self._pool,
                "active": self._pool - len(self._parked),
                "parked": sorted(self._parked),
                "floor": self.cfg.floor,
                "ups": self._ups,
                "downs": self._downs,
                "suppressed_ticks": self._suppressed,
                "blocked_up": self._blocked_up,
                "blocked_down": self._blocked_down,
                "errors": self._errors,
                "min_active": self._min_active,
                "max_active": self._max_active,
                "tick": self._policy.tick,
                "cooldown": self._policy.cooldown,
            }

    def events_snapshot(self) -> List[dict]:
        with self._mu:
            return [dict(e) for e in self.events]

    # ------------------------------------------------------------ lifecycle
    def stop(self) -> None:
        self._stop_ev.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=30.0)

    # --------------------------------------------------------------- events
    def _event(self, kind: str, **fields) -> None:
        """Same wall-clock-free event discipline as resilience.py /
        deploy/watcher.py: in-memory list + optional JSONL line
        (DISTACC.md schema table)."""
        rec = {"kind": kind, "model": self._model}
        rec.update(fields)
        with self._mu:
            self.events.append(rec)
        path = self.cfg.event_log
        if path:
            with self._ev_mu:
                with open(path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                    f.flush()
