"""Per-model execution engine: one deploy-form net, one jitted forward,
one compile-cache entry per warmed bucket shape.

A ModelRunner owns everything device-side for a registered model: the
Net, its params (randomly initialized or warm-started via
classify.load_pretrained), and a single jit-compiled forward whose
per-shape specializations ARE the bucket set.  `warmup()` runs every
bucket once at load so steady traffic never compiles;
`compile_count()` reads the jit cache size, which is how the
bounded-compile guarantee is asserted (tests/test_serving.py soak) —
on top of SPARKNET_COMPILE_CACHE persistence (utils/compile_cache.py),
which makes even the warmup compiles cross-process warm starts.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..classify import load_pretrained, probability_blob
from ..obs.trace import device_annotation
from .buckets import bucket_sizes, validate_buckets


def resolve_net_param(spec: str, *, max_batch: int = 8):
    """`spec` -> deploy-form NetParameter: a model-zoo name (models/
    __init__.py registry, deploy=True) or a deploy .prototxt path.
    A zoo name whose builder family has no deploy form dies with a
    ValueError naming the model, not a TypeError from the builder."""
    from ..models import get_model, model_names

    if spec in model_names():
        try:
            return get_model(spec, batch=int(max_batch), deploy=True)
        except TypeError as e:
            raise ValueError(
                f"model-zoo entry {spec!r} has no deploy form: {e}") from e
    if os.path.exists(spec):
        from ..proto import caffe_pb

        return caffe_pb.load_net_prototxt(spec)
    raise ValueError(
        f"model spec {spec!r} is neither a model-zoo name "
        f"({sorted(model_names())}) nor an existing prototxt path")


class ModelRunner:
    """Jitted TEST-phase forward over a fixed bucket ladder.

    Single-threaded by design: exactly one batcher thread per model calls
    `forward_padded` (serving/server.py), so no lock is taken here.

    With `shards` > 1 the runner is SHARDED: `device` is a mesh slice (a
    list of exactly `shards` devices), params ride `NamedSharding`s over
    a (1, shards) `make_mesh` grid (the SAME mesh axes training's
    GspmdTrainer uses — parallel/gspmd.py), and the forward jits with
    gspmd in/out shardings so each device stores 1/shards of every big
    blob at rest and XLA inserts the all-gathers that materialize them
    at use (see _build_exec for why gather-at-use is the bitwise-safe
    partitioning).  The partition policy is training's `infer_tp_specs`
    verbatim: output-feature dim 0 of blobs >= `tp_min_elems` that
    divide evenly, biases following their weights, everything else
    replicated."""

    def __init__(self, net_param, *, weights: Optional[str] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_batch: int = 8, seed: int = 0,
                 device=None, quant: Optional[str] = None,
                 quant_calib_batches: int = 2,
                 quant_min_agreement: Optional[float] = None,
                 shards: int = 1,
                 tp_min_elems: int = 1 << 16,
                 capture_blob: Optional[str] = None,
                 data_shapes: Optional[Dict] = None) -> None:
        import jax

        from ..core.net import Net
        from .quant import validate_quant_mode

        self.buckets: Tuple[int, ...] = (
            validate_buckets(buckets) if buckets is not None
            else bucket_sizes(max_batch))
        self.quant = validate_quant_mode(quant)
        self.quant_agreement: Optional[float] = None
        self._seed = int(seed)
        self.shards = int(shards)
        if self.shards < 1:
            raise ValueError(
                f"shards must be >= 1, got {self.shards}")
        self.tp_min_elems = int(tp_min_elems)
        # data_shapes: explicit shapes for data blobs the builder cannot
        # infer (no crop_size, no readable store) — the offline
        # featurizer app's `extra_shapes` passthrough
        self.net = Net(net_param, "TEST", data_shapes=data_shapes)
        self.params = self.net.init_params(seed)
        if weights:
            self.params = load_pretrained(self.net, self.params, weights)
        if self.shards > 1:
            self.device = None
            self._bind_slice(device if device is not None
                             else jax.devices()[:self.shards])
            self.params = self._shard_params(self.params)
        else:
            self.slice_devices = None
            self.device = device
            if device is not None:
                # pin params to the target device; jit then executes
                # there (bench.py's serving leg forces the CPU backend
                # this way even when the process default platform is the
                # TPU tunnel)
                self.params = jax.device_put(self.params, device)
        self.input_blob = self.net.input_blobs[0]
        self.sample_shape: Tuple[int, ...] = tuple(
            self.net.blob_shapes[self.input_blob][1:])
        self.capture_blob = capture_blob
        if capture_blob is None:
            self.output_blob = probability_blob(self.net)
            self.n_outputs = int(
                self.net.blob_shapes[self.output_blob][-1])
        else:
            # featurization mode: read back an INTERMEDIATE blob through
            # the same jit/bucket/quant machinery the score path uses
            # (the served replacement for featurizer_app's ad-hoc jit).
            # The captured activation is flattened to (batch, -1) so the
            # server's (bucket, n_outputs) response contract holds for
            # conv feature maps too.
            shape = self.net.blob_shapes.get(capture_blob)
            if shape is None:
                raise ValueError(
                    f"capture_blob {capture_blob!r} is not a blob of "
                    f"this net; available: "
                    f"{sorted(self.net.blob_shapes)}")
            if len(shape) < 2:
                raise ValueError(
                    f"capture_blob {capture_blob!r} has shape "
                    f"{tuple(shape)} with no per-row feature axis; "
                    f"capture needs a (batch, ...) activation")
            self.output_blob = capture_blob
            self.n_outputs = int(np.prod(shape[1:]))
        self._build_exec()
        if self.quant != "fp32":
            self.calibrate_quant(quant_calib_batches,
                                 min_agreement=quant_min_agreement)

    # ------------------------------------------------------- sharded plumbing
    def _bind_slice(self, devices) -> None:
        """Bind this runner to a mesh slice: exactly `shards` devices,
        one (1, shards) mesh over them, and the per-param
        PartitionSpecs.  Called at construction and by replicate() when
        cloning onto a different slice (the pspecs depend only on the
        net + shard count, so every slice of every generation partitions
        identically — a rebuild lands bitwise on the same sub-mesh)."""
        from ..parallel.gspmd import infer_tp_specs
        from ..parallel.mesh import make_mesh

        devs = list(devices)
        if len(devs) != self.shards:
            raise ValueError(
                f"sharded runner needs a device slice of exactly "
                f"{self.shards} device(s), got {len(devs)}; on the CPU "
                f"test platform export "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=8")
        self.slice_devices = devs
        self._mesh = make_mesh(n_workers=1, model_parallel=self.shards,
                               devices=devs)
        self._pspecs = infer_tp_specs(self.net, self._mesh,
                                      min_tp_elems=self.tp_min_elems)

    def _repl_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._mesh, P())

    def _shard_params(self, params):
        """device_put the fp32 param tree onto the slice with its
        per-param NamedShardings (the gspmd trainer's placement recipe,
        parallel/gspmd.py GspmdTrainer.__init__)."""
        import jax
        from jax.sharding import NamedSharding

        return {k: jax.device_put(v,
                                  NamedSharding(self._mesh,
                                                self._pspecs[k]))
                for k, v in params.items()}

    def _qtree_specs(self, qtree):
        """Leaf-level PartitionSpecs for a quantized exec tree,
        mirroring the fp32 pspecs: an int8-packed {"q", "scale"} leaf
        inherits the weight's spec for "q" and shards its 1-D
        per-output-channel "scale" over the same axis."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import MODEL_AXIS

        specs = {}
        for key, val in qtree.items():
            ps = self._pspecs.get(key, P())
            if isinstance(val, dict):
                specs[key] = {"q": ps,
                              "scale": (P(MODEL_AXIS)
                                        if len(ps) and ps[0] == MODEL_AXIS
                                        else P())}
            else:
                specs[key] = ps
        return specs

    def tp_sharded_params(self) -> Dict[str, Tuple[int, ...]]:
        """Which parameters actually shard over the model axis (empty
        for unsharded runners) — introspection for tests/stats, same
        shape as GspmdTrainer.tp_sharded_params."""
        from jax.sharding import PartitionSpec as P

        if self.shards <= 1:
            return {}
        return {k: tuple(self.net.param_inits[k].shape)
                for k, s in self._pspecs.items() if s != P()}

    def _build_exec(self) -> None:
        """Build the device-side execution state from self.params/device:
        the (possibly quantized) exec tree and a FRESH jitted forward —
        so each replica owns its own jit cache and compile_count() stays
        an honest per-device bound."""
        import jax
        import jax.numpy as jnp

        from .quant import build_quantized_params, quantized_bytes

        net = self.net
        aux_blobs = list(net.input_blobs[1:])
        input_blob, output_blob = self.input_blob, self.output_blob
        flatten_out = self.capture_blob is not None

        if self.shards > 1:
            # bitwise contract of sharded serving: params live SHARDED
            # at rest (each device holds 1/shards of every big blob —
            # the memory-capacity win) and are all-gathered in-program
            # at use.  An all-gather is a pure concat of exactly the
            # master's values, so every downstream op is the
            # single-device program verbatim and the output is bitwise-
            # identical BY CONSTRUCTION — unlike activation tensor
            # parallelism, whose sharded contractions re-order fp32
            # partial sums (measured 1e-7-level drift on this backend)
            # and can never meet the bitwise bar.  int8 packed params
            # gather as int8, shrinking the cross-slice gather 4x.
            repl_sh = self._repl_sharding()

            def stage(tree):
                return jax.tree_util.tree_map(
                    lambda v: jax.lax.with_sharding_constraint(
                        v, repl_sh), tree)
        else:
            stage = None

        def fwd(params, x):
            # labels the serving forward's XLA ops when
            # SPARKNET_JAX_ANNOTATE=1 (inert nullcontext otherwise —
            # profiler RPCs can wedge the axon tunnel)
            with device_annotation("sparknet.serve_forward"):
                feed = {input_blob: x}
                # auxiliary declared inputs ride along zero-filled at
                # their declared shapes, exactly as
                # Classifier._forward_probs does
                for b in aux_blobs:
                    feed[b] = jnp.zeros(
                        net.blob_shapes[b],
                        jnp.int32 if len(net.blob_shapes[b]) == 1
                        else jnp.float32)
                y = net.forward(params, feed)[output_blob]
                if flatten_out:
                    y = y.reshape((y.shape[0], -1))
                return y

        if self.shards > 1:
            # params carry their NamedShardings in, the (small) score
            # matrix comes back replicated over the slice, and XLA
            # inserts the gathers in between — no manual communication
            # code, the GspmdTrainer placement recipe applied to
            # inference
            from jax.sharding import NamedSharding

            repl = self._repl_sharding()
            param_sh = {k: NamedSharding(self._mesh, self._pspecs[k])
                        for k in self.params}
            sharded_jit = lambda f, in0: jax.jit(    # noqa: E731
                f, in_shardings=(in0, repl), out_shardings=repl)

            def sfwd(params, x):
                return fwd(stage(params), x)
        else:
            sharded_jit = None
            sfwd = fwd

        if self.quant == "fp32":
            self._exec_params = self.params
            self._jfwd = (sharded_jit(sfwd, param_sh) if sharded_jit
                          else jax.jit(fwd))
        else:
            # fp32 stays the master copy (calibration, interchange,
            # reload); the quantized tree is what the hot path carries
            qtree, dequant = build_quantized_params(self.params, self.quant)
            if self.shards > 1:
                qspecs = self._qtree_specs(qtree)
                qsh = jax.tree_util.tree_map(
                    lambda s: NamedSharding(self._mesh, s), qspecs)
                qtree = jax.device_put(qtree, qsh)
            elif self.device is not None:
                qtree = jax.device_put(qtree, self.device)
            self._exec_params = qtree

            def qfwd(qp, x):
                # gather BEFORE dequant: the cross-slice bytes are the
                # packed int8 + per-channel scales, 4x less than fp32
                p = dequant(stage(qp) if stage else qp)
                return fwd(p, x.astype(jnp.bfloat16)).astype(jnp.float32)

            if sharded_jit:
                self._jfwd = sharded_jit(qfwd, qsh)
                self._jref = sharded_jit(sfwd, param_sh)
            else:
                self._jfwd = jax.jit(qfwd)
                self._jref = jax.jit(fwd)  # fp32 reference for calibration
        self.param_bytes = quantized_bytes(self._exec_params)
        self._shapes_seen: set = set()

    def replicate(self, device) -> "ModelRunner":
        """A sibling runner pinned to `device`: shares the Net and the
        host/master param values (one transfer, no re-init, no weights
        re-read) but owns its own exec tree and jit cache, so replicas
        compile independently and their math is bitwise-identical —
        same params, same program, different chip.  Quantization is
        re-derived from the same fp32 master (deterministic), so the
        calibration agreement carries over untouched.  For a sharded
        runner `device` is a mesh slice (list of `shards` devices) and
        the clone re-places the same master params with the same
        PartitionSpecs on its own mesh."""
        import copy

        import jax

        clone = copy.copy(self)
        if self.shards > 1:
            clone._bind_slice(device)
            clone.params = clone._shard_params(self.params)
        else:
            clone.device = device
            clone.params = jax.device_put(self.params, device)
        clone._build_exec()
        clone.quant_agreement = self.quant_agreement
        return clone

    # ------------------------------------------------------------- execution
    def _put_input(self, x: np.ndarray):
        """Stage a host batch for the jitted forward: pinned to the
        runner's device (unsharded), replicated over the slice mesh
        (sharded — every shard sees the whole batch; the params are what
        partitions), or left to the default placement."""
        import jax
        import jax.numpy as jnp

        if self.shards > 1:
            return jax.device_put(x, self._repl_sharding())
        if self.device is not None:
            return jax.device_put(x, self.device)
        return jnp.asarray(x)

    def forward_padded(self, x: np.ndarray) -> np.ndarray:
        """(bucket, *sample_shape) float32 -> (bucket, n_outputs) float32
        on the host.  The bucket-shape contract is the caller's (server
        pads before calling); an off-ladder batch still computes but
        costs a fresh compile, so it is rejected loudly instead."""
        if tuple(x.shape[1:]) != self.sample_shape:
            raise ValueError(
                f"sample shape {tuple(x.shape[1:])} != model input "
                f"{self.sample_shape}")
        if len(x) not in self.buckets:
            raise ValueError(
                f"batch {len(x)} is not a warmed bucket {self.buckets}; "
                f"pad with buckets.pad_to_bucket first")
        xj = self._put_input(x)
        self._shapes_seen.add(tuple(x.shape))
        # np.asarray is a VALUE fetch: on the tunneled platform
        # block_until_ready returns before deferred execution completes
        # (BENCH_NOTES.md round-3 trap), and a response is host data
        # anyway
        return np.asarray(self._jfwd(self._exec_params, xj))

    def forward_padded_with(self, params, x: np.ndarray) -> np.ndarray:
        """forward_padded under an ALTERNATE fp32 param tree through this
        runner's already-compiled program (same bucket shapes, so no new
        compile) — the promotion gate's primitive (deploy/watcher.py):
        a candidate training snapshot is scored against the generation
        currently serving without building a throwaway ModelRunner.
        Unlike forward_padded this mutates NO runner state (no
        _shapes_seen bookkeeping), so it is safe to call from the watcher
        thread concurrently with the batcher thread's forward_padded."""
        if tuple(x.shape[1:]) != self.sample_shape:
            raise ValueError(
                f"sample shape {tuple(x.shape[1:])} != model input "
                f"{self.sample_shape}")
        if len(x) not in self.buckets:
            raise ValueError(
                f"batch {len(x)} is not a warmed bucket {self.buckets}; "
                f"pad with buckets.pad_to_bucket first")
        # the quantized hot path's program expects a quantized tree;
        # gate through the fp32 reference program instead (the same one
        # calibration scores against)
        jfwd = self._jref if self.quant != "fp32" else self._jfwd
        return np.asarray(jfwd(params, self._put_input(x)))

    def calibrate_quant(self, n_batches: int = 2, *,
                        min_agreement: Optional[float] = None,
                        ) -> Optional[float]:
        """Measure the quantized forward's top-1 agreement against the
        fp32 master on seeded synthetic batches at the largest bucket
        (the serving analogue of PTQ calibration data — this box has no
        egress, so the batches are deterministic uniform noise).  Stores
        and returns the fraction; with `min_agreement`, a quantization
        that broke the model fails the LOAD instead of serving garbage.
        No-op (None) on the fp32 path."""
        if self.quant == "fp32":
            return None
        from ..ops.quant import top1_agreement

        rng = np.random.RandomState(self._seed ^ 0x5EED)
        bucket = max(self.buckets)
        agree = []
        for _ in range(max(1, int(n_batches))):
            x = rng.rand(bucket, *self.sample_shape).astype(np.float32)
            # same device/conversion path as forward_padded, so the
            # calibration compile IS the largest warmed bucket's program
            xj = self._put_input(x)
            ref = np.asarray(self._jref(self.params, xj))
            got = np.asarray(self._jfwd(self._exec_params, xj))
            agree.append(top1_agreement(ref, got))
        self.quant_agreement = float(np.mean(agree))
        if min_agreement is not None and \
                self.quant_agreement < float(min_agreement):
            raise ValueError(
                f"quant={self.quant!r} calibration failed: top-1 "
                f"agreement {self.quant_agreement:.4f} < required "
                f"{float(min_agreement):.4f} over {n_batches} "
                f"batches of {bucket}")
        return self.quant_agreement

    def health_probe(self, seed: int = 0) -> float:
        """One seeded single-sample forward at the SMALLEST bucket,
        value-fetched; returns the latency in ms.  The half-open probe
        primitive (serving/resilience.py): exercises the same jitted
        path live traffic uses — padding, dispatch, host fetch — without
        touching scheduler state, and raises whatever the forward
        raises so the breaker sees real failures."""
        from ..obs.trace import now_s

        rng = np.random.RandomState(int(seed) & 0x7FFFFFFF)
        b = min(self.buckets)
        x = rng.rand(b, *self.sample_shape).astype(np.float32)
        t0 = now_s()
        self.forward_padded(x)
        return (now_s() - t0) * 1e3

    def warmup(self) -> int:
        """Pre-compile every bucket (zeros in, value-fetched out);
        returns the compile count afterwards, which steady-state traffic
        must never grow past."""
        for b in self.buckets:
            self.forward_padded(
                np.zeros((b,) + self.sample_shape, np.float32))
        return self.compile_count()

    def compile_count(self) -> int:
        """Distinct compiled programs behind the jitted forward.  Reads
        the jit cache size (counts recompiles our own bookkeeping could
        miss); falls back to the shapes-seen set on jax versions without
        the introspection hook."""
        try:
            return int(self._jfwd._cache_size())
        except Exception:
            return len(self._shapes_seen)

    def describe(self) -> Dict[str, object]:
        out = {"input_blob": self.input_blob,
               "sample_shape": list(self.sample_shape),
               "output_blob": self.output_blob,
               "n_outputs": self.n_outputs,
               "buckets": list(self.buckets),
               "compiles": self.compile_count(),
               "quant": self.quant,
               "quant_agreement": self.quant_agreement,
               "param_bytes": self.param_bytes,
               "shards": self.shards,
               "capture_blob": self.capture_blob}
        if self.shards > 1:
            out["slice_devices"] = [str(d) for d in self.slice_devices]
            out["tp_params"] = sorted(self.tp_sharded_params())
        return out
