"""Per-request serving observability, in the spirit of data/counters.py:
one thread-safe accumulator per model, snapshot()-able into a JSON-ready
dict that server.stats() exposes and bench.py lands in its one-line
record.

Since the obs/ unification this is a facade over a private
`obs.metrics.MetricsRegistry`: request dispositions are labeled
`serving_requests{disposition=...}` counters and the four latency legs
are `serving_latency_ms{leg=...}` bounded-reservoir histograms (the
`LatencySeries` semantics — count/mean/max over everything, nearest-rank
percentiles over the retained last-N window — now live in
obs.metrics.Histogram and are shared with ingest/training telemetry).
The public `snapshot()` key contract is reconstructed byte-for-byte
(pinned by tests/test_serving.py), and the same numbers export as
Prometheus text via `stats.registry`.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..obs.metrics import Histogram, MetricsRegistry


class LatencySeries(Histogram):
    """Bounded last-N sample window with nearest-rank percentiles.
    Back-compat alias: a `_ms`-keyed view over obs.metrics.Histogram
    (`add()` and the `{count, mean_ms, ..., p99_ms}` summary keys are the
    original public surface)."""

    def __init__(self, cap: int = 65536) -> None:
        super().__init__("latency_ms", window=cap)

    def summary(self) -> Dict[str, float]:  # type: ignore[override]
        """count/mean/max over everything observed; percentiles over the
        retained window.  All-zero when nothing was observed — the
        zero-request path must report zeros, never KeyError."""
        return super().summary(key_suffix="_ms")


class ModelStats:
    """Thread-safe serving counters for one registered model: request
    dispositions, batch occupancy, per-bucket dispatch counts, and the
    four latency legs of a request's life (queue wait -> batch assembly
    -> device -> total)."""

    SERIES = ("queue_wait", "assembly", "device", "total")
    REJECTS = ("rejected_overload", "rejected_deadline",
               "rejected_closed", "rejected_shed",
               # fragments of an aborted compound discarded before
               # dispatch (all-or-nothing cancellation, serving/compound.py)
               "rejected_compound")
    BREAKER_STATES = {"closed": 0, "open": 1, "half_open": 2}

    def __init__(self, window: int = 65536) -> None:
        self._lock = threading.Lock()
        self._window = int(window)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._registry = MetricsRegistry()
            self._counts = {
                name: self._registry.counter("serving_requests",
                                             labels={"disposition": name})
                for name in ("submitted", "completed", "failed", "batches")
                + self.REJECTS}
            self._series = {
                s: self._registry.histogram("serving_latency_ms",
                                            labels={"leg": s},
                                            window=self._window)
                for s in self.SERIES}
            self._occupancy_sum = self._registry.counter(
                "serving_batch_occupancy_sum")
            self._bucket_counts: Dict[int, object] = {}
            # per-replica mesh telemetry (created lazily on first
            # observe_replica — single-replica models keep the exact
            # PR-5 metric set, and snapshot() never includes these so
            # its byte-pinned zero-state contract holds)
            self._replica_queue: Dict[int, object] = {}
            self._replica_inflight: Dict[int, object] = {}
            self._replica_dispatches: Dict[int, object] = {}
            # breaker-state gauges (lazy like the replica gauges, so
            # resilience-off servers keep the exact metric set)
            self._breaker_state: Dict[int, object] = {}
            # shed-controller / autoscaler sensor gauges (lazy —
            # created on first observe_sensors, so pre-resilience
            # servers keep the exact metric set and snapshot() stays
            # byte-pinned)
            self._sensors: Dict[str, object] = {}

    @property
    def registry(self) -> MetricsRegistry:
        """The backing metrics registry (for Prometheus-text export)."""
        with self._lock:
            return self._registry

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            if name not in self._counts:
                raise ValueError(f"unknown serving counter {name!r}; one "
                                 f"of {sorted(self._counts)}")
            c = self._counts[name]
        c.inc(int(n))

    def value(self, name: str) -> int:
        """Current value of one disposition counter (span attributes
        carry these at record time)."""
        with self._lock:
            if name not in self._counts:
                raise ValueError(f"unknown serving counter {name!r}; one "
                                 f"of {sorted(self._counts)}")
            c = self._counts[name]
        return int(c.value)

    def observe_batch(self, n_live: int, bucket: int) -> None:
        """One dispatched micro-batch: occupancy = live rows / bucket
        rows (padding waste is 1 - occupancy)."""
        with self._lock:
            b = self._bucket_counts.get(int(bucket))
            if b is None:
                b = self._registry.counter("serving_bucket_dispatches",
                                           labels={"bucket": str(bucket)})
                self._bucket_counts[int(bucket)] = b
            batches = self._counts["batches"]
        batches.inc(1)
        self._occupancy_sum.inc(n_live / float(bucket))
        b.inc(1)

    def observe_replica(self, idx: int, queued: int, inflight: int,
                        dispatched: int = 0) -> None:
        """Mesh-serving gauges for one replica slot: live queue depth and
        in-flight rows (`serving_replica_queue_depth{replica=i}` /
        `serving_replica_inflight{replica=i}`, Gauge max tracks the
        high-water mark), plus a dispatch counter when a batch launches.
        These ride the same private registry, so they land in the
        Prometheus export and replica_breakdown() without widening the
        byte-pinned snapshot()."""
        i = int(idx)
        with self._lock:
            q = self._replica_queue.get(i)
            if q is None:
                lbl = {"replica": str(i)}
                q = self._registry.gauge("serving_replica_queue_depth",
                                         labels=lbl)
                self._replica_queue[i] = q
                self._replica_inflight[i] = self._registry.gauge(
                    "serving_replica_inflight", labels=lbl)
                self._replica_dispatches[i] = self._registry.counter(
                    "serving_replica_dispatches", labels=lbl)
            f = self._replica_inflight[i]
            d = self._replica_dispatches[i]
        q.set(int(queued))
        f.set(int(inflight))
        if dispatched:
            d.inc(int(dispatched))

    def observe_breaker(self, idx: int, state: str) -> None:
        """Circuit-breaker state gauge for one replica slot
        (`serving_replica_breaker_state{replica=i}`: 0 closed, 1 open,
        2 half_open — resilience.py records every transition).  Rides
        the private registry like the replica gauges, so the byte-pinned
        snapshot() contract is untouched."""
        code = self.BREAKER_STATES.get(state)
        if code is None:
            raise ValueError(f"unknown breaker state {state!r}; one of "
                             f"{sorted(self.BREAKER_STATES)}")
        i = int(idx)
        with self._lock:
            g = self._breaker_state.get(i)
            if g is None:
                g = self._registry.gauge("serving_replica_breaker_state",
                                         labels={"replica": str(i)})
                self._breaker_state[i] = g
        g.set(code)

    SENSOR_GAUGES = ("serving_queue_fraction",
                     "serving_interactive_ewma_ms",
                     "serving_active_replicas")

    def observe_sensors(self, queue_fraction=None,
                        interactive_ewma_ms=None,
                        active_replicas=None) -> None:
        """The shed controller's sensors — lane queue fraction and the
        interactive total-latency EWMA — plus the autoscaler's active
        replica count, exported as NAMED gauges
        (`serving_queue_fraction` / `serving_interactive_ewma_ms` /
        `serving_active_replicas`) in the same private registry, so the
        autoscaler, the shedder, and an operator scraping the
        Prometheus text all read the one set of numbers.  Lazy like the
        replica gauges: snapshot()'s byte-pinned key contract is
        untouched."""
        updates = (("serving_queue_fraction", queue_fraction),
                   ("serving_interactive_ewma_ms", interactive_ewma_ms),
                   ("serving_active_replicas", active_replicas))
        for name, v in updates:
            if v is None:
                continue
            with self._lock:
                g = self._sensors.get(name)
                if g is None:
                    g = self._registry.gauge(name)
                    self._sensors[name] = g
            g.set(float(v))

    def sensor_values(self) -> Dict[str, float]:
        """Current sensor-gauge values (only the ones ever observed) —
        the autoscaler drill's one-set-of-numbers check."""
        with self._lock:
            return {name: float(g.value)
                    for name, g in sorted(self._sensors.items())}

    def replica_breakdown(self) -> Dict[str, Dict[str, object]]:
        """replica index (str) -> {queued_now, queued_max, inflight_now,
        inflight_max, dispatches}.  Empty for single-replica models that
        never saw observe_replica — callers gate on truthiness."""
        with self._lock:
            out: Dict[str, Dict[str, object]] = {}
            for i in sorted(self._replica_queue):
                q = self._replica_queue[i]
                f = self._replica_inflight[i]
                d = self._replica_dispatches[i]
                out[str(i)] = {"queued_now": int(q.value),
                               "queued_max": int(q.max),
                               "inflight_now": int(f.value),
                               "inflight_max": int(f.max),
                               "dispatches": int(d.value)}
                b = self._breaker_state.get(i)
                if b is not None:
                    out[str(i)]["breaker_state"] = int(b.value)
            return out

    def observe_request(self, queue_wait_ms: float, assembly_ms: float,
                        device_ms: float, total_ms: float) -> None:
        with self._lock:
            completed = self._counts["completed"]
            series = self._series
        completed.inc(1)
        series["queue_wait"].observe(queue_wait_ms)
        series["assembly"].observe(assembly_ms)
        series["device"].observe(device_ms)
        series["total"].observe(total_ms)

    def latency_summary(self, leg: str = "total") -> Dict[str, float]:
        """Summary of ONE latency leg (count/mean/max/p50/p95/p99, _ms
        keys) — the promotion watcher's pre/post-swap p99 probe reads
        this without paying for a full snapshot()."""
        with self._lock:
            s = self._series.get(leg)
            if s is None:
                raise ValueError(f"unknown latency leg {leg!r}; one of "
                                 f"{sorted(self._series)}")
        return s.summary(key_suffix="_ms")

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {name: int(c.value)
                                      for name, c in self._counts.items()}
            batches = out["batches"]
            out["batch_occupancy_mean"] = round(
                self._occupancy_sum.value / batches, 4) if batches else 0.0
            out["bucket_counts"] = {str(k): int(c.value) for k, c in
                                    sorted(self._bucket_counts.items())}
            for s in self.SERIES:
                out[f"{s}_ms"] = self._series[s].summary(key_suffix="_ms")
            return out
