"""Per-request serving observability, in the spirit of data/counters.py:
one thread-safe accumulator per model, snapshot()-able into a JSON-ready
dict that server.stats() exposes and bench.py lands in its one-line
record.

Latency series keep a bounded ring of samples (last-N window) and report
nearest-rank percentiles; like IngestCounters after the zero-round fix,
every documented key exists from birth with a zero value, so a model
that never served a request still snapshots cleanly.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List


class LatencySeries:
    """Bounded last-N sample window with nearest-rank percentiles.
    NOT internally locked — the owning ModelStats serializes access."""

    def __init__(self, cap: int = 65536) -> None:
        self._cap = int(cap)
        self._samples: List[float] = []
        self._next = 0          # ring write cursor once the window is full
        self._count = 0
        self._max = 0.0
        self._sum = 0.0         # over ALL observations, not just the window

    def add(self, ms: float) -> None:
        v = float(ms)
        if len(self._samples) < self._cap:
            self._samples.append(v)
        else:
            self._samples[self._next] = v
            self._next = (self._next + 1) % self._cap
        self._count += 1
        self._sum += v
        self._max = max(self._max, v)

    def summary(self) -> Dict[str, float]:
        """count/mean/max over everything observed; percentiles over the
        retained window.  All-zero when nothing was observed — the
        zero-request path must report zeros, never KeyError."""
        if not self._count:
            return {"count": 0, "mean_ms": 0.0, "max_ms": 0.0,
                    "p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
        s = sorted(self._samples)

        def rank(q: float) -> float:
            return s[min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))]

        return {"count": self._count,
                "mean_ms": round(self._sum / self._count, 4),
                "max_ms": round(self._max, 4),
                "p50_ms": round(rank(0.50), 4),
                "p95_ms": round(rank(0.95), 4),
                "p99_ms": round(rank(0.99), 4)}


class ModelStats:
    """Thread-safe serving counters for one registered model: request
    dispositions, batch occupancy, per-bucket dispatch counts, and the
    four latency legs of a request's life (queue wait -> batch assembly
    -> device -> total)."""

    SERIES = ("queue_wait", "assembly", "device", "total")
    REJECTS = ("rejected_overload", "rejected_deadline", "rejected_closed")

    def __init__(self, window: int = 65536) -> None:
        self._lock = threading.Lock()
        self._window = int(window)
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._counts = {"submitted": 0, "completed": 0, "failed": 0,
                            "batches": 0}
            for r in self.REJECTS:
                self._counts[r] = 0
            self._series = {s: LatencySeries(self._window)
                            for s in self.SERIES}
            self._occupancy_sum = 0.0
            self._bucket_counts: Dict[int, int] = {}

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            if name not in self._counts:
                raise ValueError(f"unknown serving counter {name!r}; one "
                                 f"of {sorted(self._counts)}")
            self._counts[name] += int(n)

    def observe_batch(self, n_live: int, bucket: int) -> None:
        """One dispatched micro-batch: occupancy = live rows / bucket
        rows (padding waste is 1 - occupancy)."""
        with self._lock:
            self._counts["batches"] += 1
            self._occupancy_sum += n_live / float(bucket)
            self._bucket_counts[int(bucket)] = \
                self._bucket_counts.get(int(bucket), 0) + 1

    def observe_request(self, queue_wait_ms: float, assembly_ms: float,
                        device_ms: float, total_ms: float) -> None:
        with self._lock:
            self._counts["completed"] += 1
            self._series["queue_wait"].add(queue_wait_ms)
            self._series["assembly"].add(assembly_ms)
            self._series["device"].add(device_ms)
            self._series["total"].add(total_ms)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = dict(self._counts)
            batches = self._counts["batches"]
            out["batch_occupancy_mean"] = round(
                self._occupancy_sum / batches, 4) if batches else 0.0
            out["bucket_counts"] = {str(k): v for k, v in
                                    sorted(self._bucket_counts.items())}
            for s in self.SERIES:
                out[f"{s}_ms"] = self._series[s].summary()
            return out
