"""Replica scheduler: least-loaded routing + continuous batch refill.

PR 5's batcher ran ONE thread per model pulling from ONE queue.Queue,
blocking up to `max_wait_ms` to top a batch off before dispatch — so a
lone request always paid the full coalesce window, and a second device
could never help.  This module replaces that loop with the
continuous-batching discipline the bucketed-shape + warmup machinery
(buckets.py, engine.warmup) was built to enable:

- Admission routes every request to the LEAST-LOADED replica (queued +
  in-flight, round-robin tie-break so equally-idle replicas interleave
  deterministically).
- One worker per replica sleeps on a shared condition variable and is
  woken the moment work lands — no idle polling, no fixed wait: it pops
  whatever is pending (up to max_batch) and dispatches IMMEDIATELY.
  Batches form naturally while a replica is busy: everything that
  arrived during the in-flight dispatch becomes the next batch the
  instant the replica frees.  `min_fill > 1` optionally restores a
  bounded coalesce window (wait up to max_wait_ms for min_fill requests)
  for throughput-over-latency deployments.

The scheduler is deliberately model-agnostic: it moves opaque items and
counts load; padding, deadlines, stats, and the jitted forward all stay
in serving/server.py's run callback, which executes OUTSIDE the lock so
admission/routing never stalls behind device time.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Callable, Deque, List, Optional, Sequence, Tuple

from ..obs.trace import now_s

__all__ = ["ReplicaScheduler", "SchedulerFull", "SchedulerClosed",
           "default_submit_timeout_s", "SUBMIT_TIMEOUT_ENV"]

SUBMIT_TIMEOUT_ENV = "SPARKNET_SERVE_SUBMIT_TIMEOUT_S"


def default_submit_timeout_s() -> float:
    """SPARKNET_SERVE_SUBMIT_TIMEOUT_S: the bound on blocking
    submit(wait=True) backpressure when the caller passes no explicit
    timeout_s.  Before this knob an omitted timeout blocked the client
    thread FOREVER on a saturated lane; now it surfaces as the same
    SchedulerFull / 503 the non-blocking path raises."""
    raw = os.environ.get(SUBMIT_TIMEOUT_ENV, "30")
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{SUBMIT_TIMEOUT_ENV}={raw!r} is not a number")
    if v <= 0:
        raise ValueError(f"{SUBMIT_TIMEOUT_ENV} must be > 0, got {v}")
    return v


class SchedulerFull(Exception):
    """Total pending reached queue_depth (server maps to
    ServerOverloaded — the 503)."""


class SchedulerClosed(Exception):
    """stop() was called (server maps to ServerClosed)."""


class ReplicaScheduler:
    """N per-replica pending deques + N worker threads behind one
    condition variable.

    `run(replica_idx, batch)` is the dispatch callback; it runs outside
    the lock and must not raise (the server's callback resolves every
    future itself, exceptions included)."""

    def __init__(self, n_replicas: int, *,
                 max_batch: int, queue_depth: int,
                 run: Callable[[int, List], None],
                 min_fill: int = 1, max_wait_ms: float = 0.0,
                 name: str = "model") -> None:
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if not 1 <= min_fill <= max_batch:
            raise ValueError(
                f"min_fill must be in [1, max_batch={max_batch}], "
                f"got {min_fill}")
        self.n_replicas = int(n_replicas)
        self.max_batch = int(max_batch)
        self.queue_depth = int(queue_depth)
        self.min_fill = int(min_fill)
        self.max_wait_ms = float(max_wait_ms)
        self._run = run
        self._cv = threading.Condition()
        self._pending: List[Deque] = [deque() for _ in range(n_replicas)]
        self._inflight = [0] * n_replicas
        self._rr = 0                 # rotates the least-loaded tie-break
        self._enabled = [True] * n_replicas   # breaker-controlled routing
        self._stopping = False
        self._threads = [
            threading.Thread(target=self._worker, args=(i,),
                             name=f"sparknet-serve-{name}-r{i}",
                             daemon=True)
            for i in range(n_replicas)]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------- admission
    def submit(self, item, *, wait: bool = False,
               timeout_s: Optional[float] = None) -> int:
        """Route `item` to the least-loaded ENABLED replica; returns its
        index.  A full scheduler (total pending == queue_depth) raises
        SchedulerFull immediately, or after blocking up to `timeout_s`
        when wait=True (backpressure mode; an omitted timeout_s is
        bounded by SPARKNET_SERVE_SUBMIT_TIMEOUT_S — never an unbounded
        block)."""
        with self._cv:
            if self._stopping:
                raise SchedulerClosed("scheduler is stopping")
            if self._total_pending() >= self.queue_depth:
                if not wait:
                    raise SchedulerFull(self.queue_depth)
                if timeout_s is None:
                    timeout_s = default_submit_timeout_s()
                deadline = now_s() + float(timeout_s)
                while (self._total_pending() >= self.queue_depth
                       and not self._stopping):
                    remaining = deadline - now_s()
                    if remaining <= 0:
                        raise SchedulerFull(self.queue_depth)
                    self._cv.wait(remaining)
                if self._stopping:
                    raise SchedulerClosed("scheduler is stopping")
            i = self._pick_replica()
            self._pending[i].append(item)
            self._cv.notify_all()
            return i

    def _total_pending(self) -> int:
        return sum(len(dq) for dq in self._pending)

    def _pick_replica(self, exclude: Optional[int] = None) -> int:
        """Least (queued + in-flight) over the ENABLED replicas; ties
        rotate from the last pick so a burst onto an idle mesh spreads
        one-per-replica instead of piling onto replica 0.  With every
        replica disabled (all breakers open) admission still lands
        somewhere — the item parks until a re-enable or the stop-time
        drain, which is strictly better than dropping admitted work."""
        n = self.n_replicas
        pool = [k for k in range(n)
                if self._enabled[k] and k != exclude]
        if not pool:
            pool = [k for k in range(n) if k != exclude] or list(range(n))
        i = min(pool,
                key=lambda k: (len(self._pending[k]) + self._inflight[k],
                               (k - self._rr) % n))
        self._rr = (i + 1) % n
        return i

    # -------------------------------------------------- resilience control
    def set_enabled(self, i: int, enabled: bool) -> None:
        """Include/exclude replica i from routing (the circuit-breaker
        lever).  Disabling never touches items already queued on i —
        the caller drains and requeues them explicitly, so the
        exactly-once story stays in one place."""
        with self._cv:
            self._enabled[i] = bool(enabled)
            self._cv.notify_all()

    def disable_unless_last(self, i: int) -> bool:
        """Atomically disable replica i for routing UNLESS it is the
        LAST enabled replica — then leave it routed and return False.
        The check and the disable are one critical section, so two
        breakers tripping concurrently on a 2-replica lane can never
        interleave their way to zero enabled replicas (a zero-capacity
        lane parks every admitted item and hangs submit(wait=True)
        until its timeout — the respawn-in-place guard exists so that
        can never happen)."""
        with self._cv:
            if self._enabled[i] and \
                    sum(1 for e in self._enabled if e) <= 1:
                return False
            self._enabled[i] = False
            self._cv.notify_all()
            return True

    def is_enabled(self, i: int) -> bool:
        with self._cv:
            return self._enabled[i]

    def enabled_mask(self) -> List[bool]:
        with self._cv:
            return list(self._enabled)

    def enabled_count(self) -> int:
        """Replicas currently included in routing — the capacity floor
        the breaker's respawn-in-place guard and the autoscaler's
        min_replicas floor are both defined over."""
        with self._cv:
            return sum(1 for e in self._enabled if e)

    def drain_replica(self, i: int) -> List:
        """Atomically remove and return replica i's QUEUED items (the
        breaker eviction path).  In-flight work is untouched — its math
        is already launched and the run callback owns its futures."""
        with self._cv:
            items = list(self._pending[i])
            self._pending[i].clear()
            self._cv.notify_all()
            return items

    def discard(self, pred: Callable[[object], bool]) -> List:
        """Atomically remove and return every QUEUED item matching
        `pred`, across all replicas (the compound-request abort lever:
        when one fragment of an all-or-nothing compound 503s/504s, its
        sibling fragments still waiting in queues are pure waste — pull
        them before a worker pops them).  In-flight items are untouched,
        same as drain_replica: their math is already launched and the
        run callback owns their futures."""
        with self._cv:
            removed: List = []
            for dq in self._pending:
                kept = [it for it in dq if not pred(it)]
                if len(kept) != len(dq):
                    removed.extend(it for it in dq if pred(it))
                    dq.clear()
                    dq.extend(kept)
            if removed:
                self._cv.notify_all()    # queue space freed
            return removed

    def requeue(self, items: Sequence, *,
                exclude: Optional[int] = None) -> None:
        """Re-admit ALREADY-ADMITTED items (drained from a tripped
        replica, or a failed batch being retried) onto enabled replicas,
        least-loaded first and skipping `exclude`.  Deliberately bypasses
        queue_depth: these items passed admission once — re-rejecting or
        dropping them would break the exactly-once contract."""
        if not items:
            return
        with self._cv:
            if self._stopping:
                raise SchedulerClosed("scheduler is stopping")
            for item in items:
                self._pending[self._pick_replica(exclude)].append(item)
            self._cv.notify_all()

    # --------------------------------------------------------------- workers
    def _worker(self, i: int) -> None:
        cv = self._cv
        pending = self._pending[i]
        while True:
            with cv:
                # a disabled replica must not pop (its breaker is open)
                # — unless we are stopping, when every queue drains so
                # no admitted item is ever stranded
                while (not self._stopping
                       and (not pending or not self._enabled[i])):
                    cv.wait()
                if not pending:          # stopping and nothing left
                    return
                if (self.min_fill > 1 and len(pending) < self.min_fill
                        and not self._stopping):
                    # opt-in coalesce: wait (bounded) for a fuller batch
                    wait_end = now_s() + self.max_wait_ms / 1e3
                    while (len(pending) < self.min_fill
                           and not self._stopping):
                        remaining = wait_end - now_s()
                        if remaining <= 0:
                            break
                        cv.wait(remaining)
                take = min(self.max_batch, len(pending))
                batch = [pending.popleft() for _ in range(take)]
                self._inflight[i] += take
                cv.notify_all()          # queue space freed; drain waiters
            try:
                self._run(i, batch)
            finally:
                with cv:
                    self._inflight[i] -= take
                    cv.notify_all()

    # ------------------------------------------------------------- lifecycle
    def drain(self) -> None:
        """Block until nothing is pending or in flight (the scheduler
        stays open for more work)."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._total_pending() == 0
                and not any(self._inflight))

    def stop(self, *, drain: bool = True) -> List:
        """Stop the workers.  drain=True lets them empty their deques
        first; drain=False flushes everything still pending and returns
        it for the caller to reject.  In-flight batches always complete
        (their math is already launched).  Idempotent; joins workers."""
        with self._cv:
            self._stopping = True
            flushed: List = []
            if not drain:
                for dq in self._pending:
                    flushed.extend(dq)
                    dq.clear()
            self._cv.notify_all()
        # bounded join: a worker stuck in device math (wedged tunnel)
        # must not hang shutdown forever — the threads are daemonic, so
        # after the timeout they die with the process; 30 s matches the
        # ingest executor's close() bound
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=30.0)
        return flushed

    # --------------------------------------------------------------- observe
    def depth(self, i: int) -> Tuple[int, int]:
        """(queued, in-flight) for replica i."""
        with self._cv:
            return len(self._pending[i]), self._inflight[i]

    def depths(self) -> List[Tuple[int, int]]:
        with self._cv:
            return [(len(self._pending[i]), self._inflight[i])
                    for i in range(self.n_replicas)]

    def queued_total(self) -> int:
        with self._cv:
            return self._total_pending()
