"""Online inference server: thread-safe request queue + dynamic
micro-batcher over bucketed shapes, with admission control and graceful
drain.

The dataflow core (core/net.py) stays untouched — this layer turns a
stream of independent single-sample requests into efficient padded-batch
dispatches, the same separation TensorFlow drew between its dataflow
runtime and the serving/batching layer in front of it (PAPERS.md:
"TensorFlow: A system for large-scale machine learning"; the reference
Caffe stack stops at offline batch scoring, classifier.py).

Per model there is ONE bounded queue and ONE batcher thread:

  submit() --admission--> queue --coalesce <= max_batch/max_wait_ms-->
    pad to bucket --> jitted forward (warmed shapes only) --> slice -->
      resolve futures

Rejections are exceptions on the returned future or raised at submit
(errors.py: ServerOverloaded at admission, DeadlineExceeded at batch
assembly, ServerClosed at shutdown).  close(drain=True) delivers every
admitted request before returning; stats() snapshots per-model latency
histograms, occupancy, and reject counts (stats.py).
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs.trace import now_s, span
from .buckets import pad_to_bucket, pick_bucket
from .errors import (DeadlineExceeded, ServerClosed, ServerOverloaded,
                     ServingError)
from .registry import LoadedModel, ModelRegistry


@dataclass
class ServerConfig:
    """Knobs of the batching/admission policy (engine-side knobs —
    buckets, weights — ride through load())."""

    max_batch: int = 8          # coalesce at most this many requests
    max_wait_ms: float = 5.0    # ... or stop waiting after this long
    queue_depth: int = 64       # admission bound; beyond -> ServerOverloaded
    default_deadline_ms: Optional[float] = None  # per-request override wins
    poll_s: float = 0.05        # batcher idle poll (shutdown latency bound)


@dataclass
class Response:
    """What a resolved future carries.  `bucket` records the padded batch
    shape the request was computed in, which makes every response exactly
    replayable: a direct net.forward at that bucket is bitwise-identical
    (XLA specializes programs per shape, so replaying at a DIFFERENT
    batch size can differ in final-ulp rounding — tests pin both facts)."""

    probs: np.ndarray
    model: str
    generation: int
    bucket: int
    batch_live: int             # real rows in the dispatched bucket
    queue_wait_ms: float
    assembly_ms: float
    device_ms: float
    total_ms: float

    @property
    def argmax(self) -> int:
        return int(np.argmax(self.probs))


@dataclass
class _Request:
    sample: np.ndarray
    future: Future
    t_submit: float
    deadline: Optional[float]   # absolute perf_counter seconds
    t_pop: float = 0.0


@dataclass
class _Lane:
    """Per-model queue + batcher thread."""

    model: LoadedModel
    queue: _queue.Queue = field(default_factory=_queue.Queue)
    thread: Optional[threading.Thread] = None
    stopping: bool = False
    draining: bool = True
    busy: bool = False          # a popped batch is being assembled/run


class InferenceServer:
    """Multi-model online scoring front-end over a ModelRegistry.

    Usage (programmatic):

        server = InferenceServer(ServerConfig(max_batch=8, max_wait_ms=4))
        server.load("lenet")                      # zoo name or prototxt
        fut = server.submit("lenet", sample)      # (C,H,W) float32
        resp = fut.result(timeout=5)              # Response
        server.close(drain=True)

    Or as a context manager (close(drain=True) on exit).
    """

    def __init__(self, config: Optional[ServerConfig] = None,
                 registry: Optional[ModelRegistry] = None) -> None:
        self.config = config or ServerConfig()
        if self.config.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.config.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self.registry = registry or ModelRegistry()
        self._lanes: Dict[str, _Lane] = {}
        self._lock = threading.Lock()
        self._accepting = True
        self._closed = False

    # ------------------------------------------------------------ lifecycle
    def load(self, name: str, spec: Optional[str] = None, *,
             weights: Optional[str] = None,
             buckets: Optional[Sequence[int]] = None,
             seed: int = 0, device=None, warmup: bool = True,
             quant: Optional[str] = None,
             quant_min_agreement: Optional[float] = None) -> LoadedModel:
        """Load + warm a model and start its batcher lane.  The bucket
        ladder defaults to powers of two up to config.max_batch."""
        if not self._accepting:
            raise ServerClosed("server is shutting down")
        lm = self.registry.load(name, spec, weights=weights,
                                buckets=buckets,
                                max_batch=self.config.max_batch,
                                seed=seed, device=device, warmup=warmup,
                                quant=quant,
                                quant_min_agreement=quant_min_agreement)
        if self.config.max_batch > max(lm.runner.buckets):
            raise ValueError(
                f"max_batch {self.config.max_batch} exceeds the largest "
                f"bucket {max(lm.runner.buckets)}")
        lane = _Lane(model=lm,
                     queue=_queue.Queue(maxsize=self.config.queue_depth))
        lane.thread = threading.Thread(
            target=self._batcher, args=(name, lane),
            name=f"sparknet-serve-{name}", daemon=True)
        with self._lock:
            old = self._lanes.get(name)
            self._lanes[name] = lane
        if old is not None:
            self._stop_lane(old, drain=True)
        lane.thread.start()
        return lm

    def unload(self, name: str, *, drain: bool = True) -> None:
        """Stop the lane (draining admitted work by default) and drop the
        model from the registry."""
        with self._lock:
            lane = self._lanes.pop(name, None)
        if lane is not None:
            self._stop_lane(lane, drain=drain)
        self.registry.unload(name)

    def reload(self, name: str) -> LoadedModel:
        """Rebuild the model in place (fresh weights file pickup, stats
        reset, generation bump).  The lane keeps running: queued requests
        before the swap complete on the old runner."""
        return self.registry.reload(name)

    def drain(self) -> None:
        """Block until every admitted request has been delivered, keeping
        the server open for more work afterwards."""
        with self._lock:
            lanes = list(self._lanes.values())
        for lane in lanes:
            while not lane.queue.empty() or lane.busy:
                time.sleep(self.config.poll_s / 2)

    def close(self, *, drain: bool = True) -> None:
        """Stop accepting; deliver (drain=True) or reject with
        ServerClosed (drain=False) everything still queued; stop lanes.
        Idempotent."""
        self._accepting = False
        if self._closed:
            return
        self._closed = True
        with self._lock:
            lanes = list(self._lanes.values())
        for lane in lanes:
            self._stop_lane(lane, drain=drain)

    def _stop_lane(self, lane: _Lane, *, drain: bool) -> None:
        lane.draining = drain
        lane.stopping = True
        if not drain:
            self._flush_reject(lane)
        if lane.thread is not None:
            lane.thread.join()
            lane.thread = None

    def _flush_reject(self, lane: _Lane) -> None:
        while True:
            try:
                req = lane.queue.get_nowait()
            except _queue.Empty:
                return
            lane.model.stats.bump("rejected_closed")
            req.future.set_exception(
                ServerClosed("server closed before this request ran"))

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    # ------------------------------------------------------------ admission
    def submit(self, model: str, sample, *,
               deadline_ms: Optional[float] = None,
               wait: bool = False,
               wait_timeout_s: Optional[float] = None) -> Future:
        """Admit one sample for scoring; returns a Future resolving to a
        Response (or raising the rejection).

        Admission is non-blocking by default: a full queue raises
        ServerOverloaded immediately (the 503 path).  wait=True turns
        overload into backpressure — block until space or
        `wait_timeout_s` (then ServerOverloaded anyway)."""
        lane = self._lane(model)
        lm = lane.model
        x = np.asarray(sample, dtype=np.float32)
        if x.shape == (int(np.prod(lm.runner.sample_shape)),):
            x = x.reshape(lm.runner.sample_shape)
        if tuple(x.shape) != lm.runner.sample_shape:
            raise ValueError(
                f"sample shape {tuple(x.shape)} != model input "
                f"{lm.runner.sample_shape} for {model!r}")
        if not self._accepting or lane.stopping:
            raise ServerClosed("server is shutting down")
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        t0 = now_s()
        req = _Request(
            sample=x, future=Future(), t_submit=t0,
            deadline=None if deadline_ms is None
            else t0 + float(deadline_ms) / 1e3)
        lm.stats.bump("submitted")
        try:
            with span("serve.submit", model=model) as sp:
                if wait:
                    lane.queue.put(req, timeout=wait_timeout_s)
                else:
                    lane.queue.put_nowait(req)
                sp.set(queued=lane.queue.qsize(),
                       submitted=lm.stats.value("submitted"))
        except _queue.Full:
            lm.stats.bump("rejected_overload")
            raise ServerOverloaded(
                f"{model!r} queue at depth {self.config.queue_depth}"
            ) from None
        return req.future

    def submit_many(self, model: str, samples, **kw) -> List[Future]:
        """Burst admission; per-sample rejections surface on the
        corresponding future instead of aborting the rest of the burst
        (submit()'s synchronous raise is per-call, so a loop would stop
        at the first overload)."""
        futs: List[Future] = []
        for s in samples:
            try:
                futs.append(self.submit(model, s, **kw))
            except ServingError as e:
                f: Future = Future()
                f.set_exception(e)
                futs.append(f)
        return futs

    def _lane(self, model: str) -> _Lane:
        with self._lock:
            lane = self._lanes.get(model)
        if lane is None:
            # registry lookup raises ModelNotLoaded with the loaded names
            self.registry.get(model)
            raise ServerClosed(f"model {model!r} has no serving lane")
        return lane

    # ------------------------------------------------------------- batching
    def _batcher(self, name: str, lane: _Lane) -> None:
        """The per-model micro-batch loop: block for a first request,
        coalesce up to max_batch/max_wait_ms more, dispatch."""
        cfg = self.config
        q = lane.queue
        while True:
            try:
                first = q.get(timeout=cfg.poll_s)
            except _queue.Empty:
                if lane.stopping:
                    return
                continue
            lane.busy = True
            try:
                with span("serve.assemble", model=name) as sp:
                    first.t_pop = now_s()
                    batch = [first]
                    window_end = first.t_pop + cfg.max_wait_ms / 1e3
                    while len(batch) < cfg.max_batch:
                        remaining = window_end - now_s()
                        if remaining <= 0 or (lane.stopping and q.empty()):
                            break
                        try:
                            nxt = q.get(timeout=remaining)
                        except _queue.Empty:
                            break
                        nxt.t_pop = now_s()
                        batch.append(nxt)
                    sp.set(batch=len(batch), queued=q.qsize())
                self._run_batch(lane, batch)
            finally:
                lane.busy = False

    def _run_batch(self, lane: _Lane, batch: List[_Request]) -> None:
        lm = lane.model
        runner, generation = lm.runner, lm.generation
        now = now_s()
        live: List[_Request] = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                lm.stats.bump("rejected_deadline")
                r.future.set_exception(DeadlineExceeded(
                    f"deadline passed {round((now - r.deadline) * 1e3, 2)}"
                    f" ms before batch launch"))
            else:
                live.append(r)
        if not live:
            return
        bucket = pick_bucket(len(live), runner.buckets)
        x = pad_to_bucket(
            np.stack([r.sample for r in live]).astype(np.float32), bucket)
        t_launch = now_s()
        try:
            with span("serve.device", model=lm.name, bucket=bucket,
                      live=len(live)):
                out = runner.forward_padded(x)
        except Exception as e:
            lm.stats.bump("failed", len(live))
            for r in live:
                r.future.set_exception(
                    ServingError(f"model {lm.name!r} forward failed: {e}"))
            return
        t_done = now_s()
        device_ms = (t_done - t_launch) * 1e3
        lm.stats.observe_batch(len(live), bucket)
        with span("serve.respond", model=lm.name, bucket=bucket,
                  live=len(live)) as sp:
            for i, r in enumerate(live):
                total_ms = (t_done - r.t_submit) * 1e3
                queue_wait_ms = (r.t_pop - r.t_submit) * 1e3
                assembly_ms = (t_launch - r.t_pop) * 1e3
                lm.stats.observe_request(queue_wait_ms, assembly_ms,
                                         device_ms, total_ms)
                r.future.set_result(Response(
                    probs=out[i], model=lm.name, generation=generation,
                    bucket=bucket, batch_live=len(live),
                    queue_wait_ms=round(queue_wait_ms, 4),
                    assembly_ms=round(assembly_ms, 4),
                    device_ms=round(device_ms, 4),
                    total_ms=round(total_ms, 4)))
            sp.set(completed=lm.stats.value("completed"),
                   batches=lm.stats.value("batches"))

    # -------------------------------------------------------------- observe
    def stats(self) -> Dict[str, object]:
        """JSON-ready snapshot: per-model serving counters/latency
        histograms (stats.py) + live queue depths + the batching
        config."""
        per_model = self.registry.stats()
        with self._lock:
            for name, lane in self._lanes.items():
                if name in per_model:
                    per_model[name]["queued_now"] = lane.queue.qsize()
        return {"models": per_model,
                "config": {"max_batch": self.config.max_batch,
                           "max_wait_ms": self.config.max_wait_ms,
                           "queue_depth": self.config.queue_depth,
                           "default_deadline_ms":
                               self.config.default_deadline_ms},
                "accepting": self._accepting}
