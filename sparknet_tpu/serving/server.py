"""Online inference server: thread-safe admission + mesh-replicated
continuous micro-batching over bucketed shapes, with admission control
and graceful drain.

The dataflow core (core/net.py) stays untouched — this layer turns a
stream of independent single-sample requests into efficient padded-batch
dispatches, the same separation TensorFlow drew between its dataflow
runtime and the serving/batching layer in front of it (PAPERS.md:
"TensorFlow: A system for large-scale machine learning"; the reference
Caffe stack stops at offline batch scoring, classifier.py).

Per model there is ONE replica scheduler (scheduler.py) over N placed
replicas (placement.py + registry replica sets):

  submit() --admission--> least-loaded replica deque --worker wakes
    (condition variable, no polling)--> pop <= max_batch NOW -->
      deadline filter --> pad to bucket --> that replica's jitted
        forward (warmed shapes only) --> slice --> resolve futures

The PR-5 batcher waited up to `max_wait_ms` to fill a batch before every
dispatch; the continuous scheduler dispatches the moment a replica is
free and lets batches form naturally WHILE replicas are busy, so a lone
request pays device time only, and a loaded mesh refills each replica's
next bucket the instant the previous one completes.  `min_fill > 1`
restores a bounded coalesce window for throughput-over-latency
deployments (max_wait_ms then caps that wait, as before).

Rejections are exceptions on the returned future or raised at submit
(errors.py: ServerOverloaded at admission, DeadlineExceeded at batch
launch, ServerClosed at shutdown).  close(drain=True) delivers every
admitted request before returning; stats() snapshots per-model latency
histograms, occupancy, reject counts (stats.py), and the per-replica
queue/in-flight breakdown.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs.trace import now_s, span
from .autoscale import AutoscaleConfig, Autoscaler
from .buckets import pad_to_bucket, pick_bucket
from .compound import (CompoundAssembler, CompoundEventLog,
                       parse_windows, validate_model_type, warp_windows)
from .errors import (DeadlineExceeded, RequestShed, ServerClosed,
                     ServerOverloaded, ServingError)
from .placement import (DevicePlacer, resolve_replica_count,
                        resolve_shard_count)
from .registry import LoadedModel, ModelRegistry
from .resilience import PRIORITIES, ResilienceConfig, ResilienceManager
from .scheduler import ReplicaScheduler, SchedulerClosed, SchedulerFull


def _default_min_fill() -> int:
    """SPARKNET_SERVE_MIN_FILL: batch rows a replica waits for (up to
    max_wait_ms) before dispatching.  1 (default) = pure continuous
    batching — dispatch whatever is pending the moment the replica
    frees."""
    try:
        return int(os.environ.get("SPARKNET_SERVE_MIN_FILL", "1"))
    except ValueError:
        raise ValueError(
            f"SPARKNET_SERVE_MIN_FILL="
            f"{os.environ.get('SPARKNET_SERVE_MIN_FILL')!r} is not an int")


@dataclass
class ServerConfig:
    """Knobs of the batching/admission policy (engine-side knobs —
    buckets, weights — ride through load())."""

    max_batch: int = 8          # coalesce at most this many requests
    max_wait_ms: float = 5.0    # min_fill coalesce cap (moot at min_fill=1)
    queue_depth: int = 64       # admission bound; beyond -> ServerOverloaded
    default_deadline_ms: Optional[float] = None  # per-request override wins
    poll_s: float = 0.05        # legacy PR-5 knob; kept so existing
    #                             ServerConfig(poll_s=...) callers construct
    min_fill: int = field(default_factory=_default_min_fill)
    # opt-in resilience control plane (serving/resilience.py): circuit
    # breakers + SLO-aware batch shedding + fault injection.  None (the
    # default) keeps every pre-resilience behavior bit-for-bit.
    resilience: Optional[ResilienceConfig] = None
    # opt-in SLO-driven autoscaler (serving/autoscale.py): load() then
    # treats `replicas` as the slot POOL and the autoscaler manages the
    # active subset.  None keeps the fixed-replica-set behavior.
    autoscale: Optional[AutoscaleConfig] = None


@dataclass
class Response:
    """What a resolved future carries.  `bucket` records the padded batch
    shape the request was computed in, which makes every response exactly
    replayable: a direct net.forward at that bucket is bitwise-identical
    (XLA specializes programs per shape, so replaying at a DIFFERENT
    batch size can differ in final-ulp rounding — tests pin both facts).
    `replica` records which placed replica ran it; replicas share param
    values, so the replica index never changes the math (also pinned)."""

    probs: np.ndarray
    model: str
    generation: int
    bucket: int
    batch_live: int             # real rows in the dispatched bucket
    queue_wait_ms: float
    assembly_ms: float
    device_ms: float
    total_ms: float
    replica: int = 0
    priority: str = "interactive"

    @property
    def argmax(self) -> int:
        return int(np.argmax(self.probs))


@dataclass
class _Request:
    sample: np.ndarray
    future: Future
    t_submit: float
    deadline: Optional[float]   # absolute now_s seconds
    t_pop: float = 0.0
    priority: str = "interactive"
    retries: int = 0            # redispatches after failed batches
    # compound fan-out bookkeeping: the owning CompoundAssembler (None
    # for plain requests) and this fragment's window index within it —
    # the discard predicate and the fan-in both key on these
    compound: Optional[object] = None
    frag: int = 0


@dataclass
class _Lane:
    """Per-model replica scheduler (+ optional resilience manager)."""

    model: LoadedModel
    sched: ReplicaScheduler
    stopping: bool = False
    resil: Optional[ResilienceManager] = None
    auto: Optional[Autoscaler] = None
    # how this lane answers: "classify" (plain rows), "detect" (compound
    # windows -> raw classifier margins + NMS), "featurize" (compound
    # rows -> capture_blob activations)
    model_type: str = "classify"


class InferenceServer:
    """Multi-model online scoring front-end over a ModelRegistry.

    Usage (programmatic):

        server = InferenceServer(ServerConfig(max_batch=8))
        server.load("lenet", replicas=4)          # spread over the mesh
        fut = server.submit("lenet", sample)      # (C,H,W) float32
        resp = fut.result(timeout=5)              # Response
        server.close(drain=True)

    Or as a context manager (close(drain=True) on exit).
    """

    def __init__(self, config: Optional[ServerConfig] = None,
                 registry: Optional[ModelRegistry] = None,
                 devices: Optional[Sequence] = None) -> None:
        self.config = config or ServerConfig()
        if self.config.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.config.queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if not 1 <= self.config.min_fill <= self.config.max_batch:
            raise ValueError(
                f"min_fill must be in [1, max_batch="
                f"{self.config.max_batch}], got {self.config.min_fill}")
        self.registry = registry or ModelRegistry()
        self._devices = devices
        self._placer: Optional[DevicePlacer] = None
        self._lanes: Dict[str, _Lane] = {}
        self._lock = threading.Lock()
        self._accepting = True
        self._closed = False
        # model -> [fn(sample, response)] observers of every delivered
        # response (the deploy TrafficLogger's tap); called on the
        # batcher thread AFTER futures resolve, so a slow/broken hook
        # delays only subsequent batches, never a client's result
        self._response_hooks: Dict[str, List] = {}
        self._hook_warned: set = set()
        # compound lifecycle events (in-memory + the optional
        # SPARKNET_SERVE_COMPOUND_LOG JSONL sink)
        self._compound_log = CompoundEventLog()

    def _get_placer(self) -> DevicePlacer:
        """Lazy so the default single-replica path never touches
        jax.devices() (no backend init just to construct a server).
        Built OUTSIDE the lock: DevicePlacer.__init__ reaches
        jax.devices(), which can block for seconds on first backend init
        (tunnel RPC) — holding _lock through that would stall every
        concurrent load/close.  Double-checked publish keeps one winner;
        a losing racer's placer is just dropped (construction is
        idempotent over the same device list)."""
        with self._lock:
            if self._placer is not None:
                return self._placer
        placer = DevicePlacer(self._devices)
        with self._lock:
            if self._placer is None:
                self._placer = placer
            return self._placer

    # ------------------------------------------------------------ lifecycle
    def load(self, name: str, spec: Optional[str] = None, *,
             weights: Optional[str] = None,
             buckets: Optional[Sequence[int]] = None,
             seed: int = 0, device=None, warmup: bool = True,
             quant: Optional[str] = None,
             quant_min_agreement: Optional[float] = None,
             replicas: Optional[int] = None,
             shards: Optional[int] = None,
             model_type: str = "classify",
             capture_blob: Optional[str] = None) -> LoadedModel:
        """Load + warm a model and start its scheduler.  `replicas`
        (default SPARKNET_SERVE_REPLICAS, normally 1; 0 = one per
        device) places that many replicas least-loaded-first across the
        device mesh; `device` pins the single-replica case explicitly
        (mutually exclusive with replicas > 1).  `shards` (default
        SPARKNET_SERVE_SHARDS, normally 1) makes each replica a mesh
        SLICE of that many devices running the engine's sharded exec
        path — placement always goes through the placer then (replicas=0
        means one replica per slice, saturating the pool), and `device`
        pinning is rejected.  The bucket ladder defaults to powers of
        two up to config.max_batch.

        `model_type` selects the lane's answer shape: "classify" (the
        default — plain submit() rows), "detect" (submit_compound()
        windows scored through the deploy net's raw classifier head),
        or "featurize" (submit_compound() rows answered with the
        `capture_blob` intermediate activation, flattened — requires
        capture_blob; the engine then reads that blob back through the
        same jit/bucket/quant machinery the score path uses)."""
        if not self._accepting:
            raise ServerClosed("server is shutting down")
        validate_model_type(model_type)
        if model_type == "featurize" and not capture_blob:
            raise ValueError(
                "model_type='featurize' needs capture_blob= (the "
                "intermediate blob whose activations are the answer)")
        if capture_blob and model_type != "featurize":
            raise ValueError(
                f"capture_blob= only applies to model_type='featurize', "
                f"not {model_type!r} (detect serves the deploy net's "
                f"own output head)")
        n_rep = resolve_replica_count(replicas, None)
        n_shards = resolve_shard_count(shards)
        devices = None
        if n_shards > 1:
            if device is not None:
                raise ValueError("pass device= (single unsharded "
                                 "replica) or shards= (sliced mesh "
                                 "placement), not both")
            placer = self._get_placer()
            if n_rep == 0:
                if len(placer) % n_shards != 0:
                    raise ValueError(
                        f"shards={n_shards} does not divide the "
                        f"{len(placer)}-device pool; sharded replicas "
                        f"need an exact tiling")
                n_rep = len(placer) // n_shards
            devices = placer.place(name, n_rep,
                                   shards_per_replica=n_shards)
        elif n_rep != 1:
            if device is not None:
                raise ValueError("pass device= (single replica) or "
                                 "replicas= (mesh placement), not both")
            placer = self._get_placer()
            if n_rep == 0:
                n_rep = len(placer)
            devices = placer.place(name, n_rep)
        try:
            lm = self.registry.load(
                name, spec, weights=weights, buckets=buckets,
                max_batch=self.config.max_batch, seed=seed,
                device=device, devices=devices, warmup=warmup,
                quant=quant, quant_min_agreement=quant_min_agreement,
                shards=n_shards, capture_blob=capture_blob)
        except Exception:
            if devices is not None:
                self._get_placer().release(name)
            raise
        if self.config.max_batch > max(lm.runner.buckets):
            raise ValueError(
                f"max_batch {self.config.max_batch} exceeds the largest "
                f"bucket {max(lm.runner.buckets)}")
        # run callback needs the lane, so sched attaches after
        lane = _Lane(model=lm, sched=None, model_type=model_type)
        lane.sched = ReplicaScheduler(
            lm.n_replicas, max_batch=self.config.max_batch,
            queue_depth=self.config.queue_depth,
            min_fill=self.config.min_fill,
            max_wait_ms=self.config.max_wait_ms,
            run=lambda i, batch: self._run_batch(lane, i, batch),
            name=name)
        if self.config.resilience is not None:
            lane.resil = ResilienceManager(
                model=name, sched=lane.sched, lm=lm,
                registry=self.registry, placer=self._placer,
                config=self.config.resilience)
        if self.config.autoscale is not None:
            # built LAST: its constructor parks the pool's tail (the
            # slots above initial_replicas) through the scheduler and
            # placer, and registers its activity gate on the manager
            lane.auto = Autoscaler(
                model=name, sched=lane.sched, lm=lm,
                registry=self.registry, placer=self._placer,
                queue_depth=self.config.queue_depth,
                resil=lane.resil, config=self.config.autoscale)
        with self._lock:
            old = self._lanes.get(name)
            self._lanes[name] = lane
        if old is not None:
            self._stop_lane(old, drain=True)
        return lane.model

    def unload(self, name: str, *, drain: bool = True) -> None:
        """Stop the scheduler (draining admitted work by default), free
        the placement slots, and drop the model from the registry."""
        with self._lock:
            lane = self._lanes.pop(name, None)
        if lane is not None:
            self._stop_lane(lane, drain=drain)
        if self._placer is not None:
            self._placer.release(name)
        self.registry.unload(name)

    def reload(self, name: str) -> LoadedModel:
        """Rebuild the model in place (fresh weights file pickup, stats
        reset, generation bump) on the SAME replica devices.  The
        scheduler keeps running: a batch dispatched before the swap
        completes on the old replica set and carries the old
        generation."""
        return self.registry.reload(name)

    def drain(self) -> None:
        """Block until every admitted request has been delivered, keeping
        the server open for more work afterwards."""
        with self._lock:
            lanes = list(self._lanes.values())
        for lane in lanes:
            lane.sched.drain()

    def close(self, *, drain: bool = True) -> None:
        """Stop accepting; deliver (drain=True) or reject with
        ServerClosed (drain=False) everything still queued; stop
        schedulers.  Idempotent."""
        self._accepting = False
        if self._closed:
            return
        self._closed = True
        with self._lock:
            lanes = list(self._lanes.values())
        for lane in lanes:
            self._stop_lane(lane, drain=drain)

    def _stop_lane(self, lane: _Lane, *, drain: bool) -> None:
        lane.stopping = True
        if lane.auto is not None:
            # autoscaler first: a scale-down mid-shutdown would drain
            # into a closing scheduler; stopping it joins the daemon so
            # no scaling action can be in flight below
            lane.auto.stop()
        if lane.resil is not None:
            # stop the maintenance thread FIRST so no probe/respawn
            # races the scheduler teardown; breakers stay frozen
            lane.resil.stop()
        for req in lane.sched.stop(drain=drain):
            lane.model.stats.bump("rejected_closed")
            req.future.set_exception(
                ServerClosed("server closed before this request ran"))

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    # ------------------------------------------------------------ admission
    def submit(self, model: str, sample, *,
               deadline_ms: Optional[float] = None,
               wait: bool = False,
               wait_timeout_s: Optional[float] = None,
               priority: str = "interactive") -> Future:
        """Admit one sample for scoring; returns a Future resolving to a
        Response (or raising the rejection).

        Admission is non-blocking by default: a full queue raises
        ServerOverloaded immediately (the 503 path).  wait=True turns
        overload into backpressure — block until space or
        `wait_timeout_s` (omitted: SPARKNET_SERVE_SUBMIT_TIMEOUT_S
        bounds the block; then ServerOverloaded anyway).

        `priority` ('interactive' | 'batch') feeds the SLO-aware shed
        controller when the server runs with a ResilienceConfig: batch
        traffic is shed (RequestShed, a 503) once the queue crosses the
        shed fraction or interactive latency breaches its SLO, so
        interactive p99 degrades LAST.  A request whose deadline is
        already unmeetable at submit (deadline_ms <= 0) is answered 504
        immediately — never queued, never device time."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        lane = self._lane(model)
        lm = lane.model
        x = np.asarray(sample, dtype=np.float32)
        if x.shape == (int(np.prod(lm.runner.sample_shape)),):
            x = x.reshape(lm.runner.sample_shape)
        if tuple(x.shape) != lm.runner.sample_shape:
            raise ValueError(
                f"sample shape {tuple(x.shape)} != model input "
                f"{lm.runner.sample_shape} for {model!r}")
        if not self._accepting or lane.stopping:
            raise ServerClosed("server is shutting down")
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        if deadline_ms is not None and float(deadline_ms) <= 0.0:
            lm.stats.bump("submitted")
            lm.stats.bump("rejected_deadline")
            if lane.resil is not None:
                lane.resil.count_deadline_drop(
                    "submit", -float(deadline_ms))
            raise DeadlineExceeded(
                f"deadline {float(deadline_ms):g} ms is already "
                f"unmeetable at submit")
        if lane.resil is not None and priority == "batch":
            queued = lane.sched.queued_total()
            reason = lane.resil.should_shed_batch(
                queued, self.config.queue_depth)
            if reason is not None:
                lm.stats.bump("submitted")
                lm.stats.bump("rejected_shed")
                lane.resil.count_shed(priority, queued, reason)
                raise RequestShed(
                    f"batch request to {model!r} shed: {reason}")
        t0 = now_s()
        req = _Request(
            sample=x, future=Future(), t_submit=t0,
            deadline=None if deadline_ms is None
            else t0 + float(deadline_ms) / 1e3,
            priority=priority)
        lm.stats.bump("submitted")
        try:
            with span("serve.submit", model=model) as sp:
                idx = lane.sched.submit(req, wait=wait,
                                        timeout_s=wait_timeout_s)
                queued, inflight = lane.sched.depth(idx)
                lm.stats.observe_replica(idx, queued, inflight)
                sp.set(replica=idx, queued=lane.sched.queued_total(),
                       submitted=lm.stats.value("submitted"))
        except SchedulerFull:
            lm.stats.bump("rejected_overload")
            raise ServerOverloaded(
                f"{model!r} queue at depth {self.config.queue_depth}"
            ) from None
        except SchedulerClosed:
            raise ServerClosed("server is shutting down") from None
        return req.future

    def submit_many(self, model: str, samples, **kw) -> List[Future]:
        """Burst admission; per-sample rejections surface on the
        corresponding future instead of aborting the rest of the burst
        (submit()'s synchronous raise is per-call, so a loop would stop
        at the first overload)."""
        futs: List[Future] = []
        for s in samples:
            try:
                futs.append(self.submit(model, s, **kw))
            except ServingError as e:
                f: Future = Future()
                f.set_exception(e)
                futs.append(f)
        return futs

    # ------------------------------------------------------------- compound
    def submit_compound(self, model: str, image, windows=None, *,
                        deadline_ms: Optional[float] = None,
                        wait: bool = False,
                        wait_timeout_s: Optional[float] = None,
                        priority: str = "interactive",
                        context_pad: int = 0,
                        crop_mode: str = "warp",
                        mean_values: Sequence[float] = (),
                        scale: float = 1.0,
                        nms_iou: float = 0.3,
                        score_min: float = 0.0) -> Future:
        """Admit ONE logical request that expands to N device rows;
        returns a Future resolving to a CompoundResponse
        (serving/compound.py) or raising the rejection.

        With `windows` (a list of [x1, y1, x2, y2] proposals), `image`
        is one (C, H, W) array: every window is context-padded, warped
        to the model's crop via the offline WindowDataFeed geometry,
        and scored — detect lanes additionally get a host-side NMS
        digest over the raw per-class margins.  Without windows,
        `image` is the raw row batch itself ((N, *sample_shape) or a
        single sample) — the featurize ingress.

        Compound semantics on the installed control planes:
        - the deadline stamps EVERY fragment (one absolute instant);
          dead-on-arrival answers 504 before any fan-out,
        - a batch-priority compound sheds WHOLE-REQUEST at admission
          (one should_shed_batch verdict for all N fragments — never
          a partial shed; interactive never sheds),
        - assembly is all-or-nothing: the first fragment 503/504
          aborts the compound, discards its queued siblings (no wasted
          device work), and the client sees ONE rejection — never a
          partial or mixed-generation response,
        - delivered fragments fire the response hooks as usual, so
          served detections flow into the TrafficLogger stream."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        lane = self._lane(model)
        if lane.model_type == "classify":
            raise ValueError(
                f"model {model!r} was loaded model_type='classify'; "
                f"compound submission needs a detect or featurize lane "
                f"(load(..., model_type=...))")
        lm = lane.model
        runner = lm.runner
        source = f"compound request to {model!r}"
        wins = None
        if windows is not None:
            wins = parse_windows(windows, source=source)
            c, h, w = runner.sample_shape
            if h != w:
                raise ValueError(
                    f"{source}: window warping needs a square model "
                    f"input, got {runner.sample_shape}")
            samples = warp_windows(
                image, wins, crop_size=h, context_pad=context_pad,
                use_square=(crop_mode == "square"),
                mean_values=mean_values, scale=scale, source=source)
        else:
            samples = np.asarray(image, dtype=np.float32)
            if samples.shape == tuple(runner.sample_shape):
                samples = samples[None]
            if samples.ndim != 1 + len(runner.sample_shape) or \
                    tuple(samples.shape[1:]) != runner.sample_shape:
                raise ValueError(
                    f"{source}: rows must be (n, "
                    f"{', '.join(map(str, runner.sample_shape))}), got "
                    f"{tuple(samples.shape)}")
            if not len(samples):
                raise ValueError(f"{source}: zero rows")
        n = len(samples)
        if not self._accepting or lane.stopping:
            raise ServerClosed("server is shutting down")
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        if deadline_ms is not None and float(deadline_ms) <= 0.0:
            lm.stats.bump("submitted", n)
            lm.stats.bump("rejected_deadline", n)
            if lane.resil is not None:
                lane.resil.count_deadline_drop(
                    "submit", -float(deadline_ms))
            raise DeadlineExceeded(
                f"deadline {float(deadline_ms):g} ms is already "
                f"unmeetable at submit")
        if lane.resil is not None and priority == "batch":
            # ONE shed verdict for the whole compound, taken before any
            # fragment admits: batch compounds shed whole-request,
            # never partially
            queued = lane.sched.queued_total()
            reason = lane.resil.should_shed_batch(
                queued, self.config.queue_depth)
            if reason is not None:
                lm.stats.bump("submitted", n)
                lm.stats.bump("rejected_shed", n)
                lane.resil.count_shed(priority, queued, reason)
                self._compound_log(
                    "compound_shed", model=model,
                    mode=lane.model_type, fragments=n,
                    priority=priority, reason=reason)
                raise RequestShed(
                    f"batch compound to {model!r} shed whole-request: "
                    f"{reason}")
        t0 = now_s()
        deadline = (None if deadline_ms is None
                    else t0 + float(deadline_ms) / 1e3)
        asm = CompoundAssembler(
            model=model, mode=lane.model_type, n=n, priority=priority,
            t_submit=t0, windows=wins, nms_iou=nms_iou,
            score_min=score_min,
            cancel=lambda a, exc: self._cancel_fragments(lane, a, exc),
            event=self._compound_log)
        frags = []
        for i in range(n):
            req = _Request(sample=np.ascontiguousarray(samples[i]),
                           future=Future(), t_submit=t0,
                           deadline=deadline, priority=priority,
                           compound=asm, frag=i)
            req.future.add_done_callback(
                lambda fut, i=i: asm.fragment_done(i, fut))
            frags.append(req)
        lm.stats.bump("submitted", n)
        self._compound_log("compound_submit", model=model,
                           mode=lane.model_type, fragments=n,
                           priority=priority,
                           windows=(len(wins) if wins is not None
                                    else None))
        with span("serve.submit_compound", model=model,
                  fragments=n) as sp:
            for i, req in enumerate(frags):
                if asm.future.done():
                    # a fast fragment already failed and aborted the
                    # compound mid-fan-out; the rest never admit
                    for r in frags[i:]:
                        r.future.set_exception(ServingError(
                            f"fragment {r.frag} never admitted: "
                            f"compound to {model!r} aborted"))
                    break
                try:
                    lane.sched.submit(req, wait=wait,
                                      timeout_s=wait_timeout_s)
                except (SchedulerFull, SchedulerClosed) as e:
                    if isinstance(e, SchedulerFull):
                        lm.stats.bump("rejected_overload")
                        exc: ServingError = ServerOverloaded(
                            f"{model!r} queue at depth "
                            f"{self.config.queue_depth} with fragment "
                            f"{i}/{n} of a compound in flight")
                    else:
                        exc = ServerClosed("server is shutting down")
                    # all-or-nothing: fail the compound, sweep the
                    # already-queued siblings, resolve the unsubmitted
                    # fragments so no future leaks unresolved
                    asm.abort(exc)
                    for r in frags[i:]:
                        if not r.future.done():
                            r.future.set_exception(ServingError(
                                f"fragment {r.frag} never admitted: "
                                f"compound to {model!r} aborted"))
                    raise exc from None
            sp.set(queued=lane.sched.queued_total())
        if asm.future.done() and asm.future.exception() is not None:
            # late stragglers: a fragment submitted before the abort
            # sweep ran may still sit queued — sweep once more
            self._cancel_fragments(lane, asm, asm.future.exception())
        return asm.future

    def _cancel_fragments(self, lane: _Lane, asm, exc) -> int:
        """Discard `asm`'s fragments still QUEUED on the lane (the
        CompoundAssembler's cancel callback).  In-flight fragments
        complete and are ignored by the sealed assembler — their math
        is already launched; the queued ones are the saved device
        work.  Discarded fragments resolve with a cancellation (their
        done-callbacks re-enter the sealed assembler and back off), so
        no future is ever left pending."""
        removed = lane.sched.discard(
            lambda it: getattr(it, "compound", None) is asm)
        if removed:
            lane.model.stats.bump("rejected_compound", len(removed))
            for r in removed:
                r.future.set_exception(ServingError(
                    f"fragment {r.frag} cancelled: compound to "
                    f"{asm.model!r} aborted ({type(exc).__name__})"))
        return len(removed)

    def compound_events(self) -> List[dict]:
        """Snapshot of the compound lifecycle event stream (submit /
        assembled / abort / shed) — the drill's and tests' handle."""
        return self._compound_log.snapshot()

    # ---------------------------------------------------------------- hooks
    def add_response_hook(self, model: str, hook) -> None:
        """Register `hook(sample, response)` to observe every DELIVERED
        response of `model` (rejections never reach hooks).  This is how
        the deploy subsystem's TrafficLogger records served traffic as a
        training stream without sitting between client and server."""
        if not callable(hook):
            raise ValueError("response hook must be callable")
        with self._lock:
            self._response_hooks.setdefault(model, []).append(hook)

    def remove_response_hook(self, model: str, hook) -> None:
        with self._lock:
            hooks = self._response_hooks.get(model, [])
            if hook in hooks:
                hooks.remove(hook)

    def _fire_response_hooks(self, model: str, pairs) -> None:
        """pairs: [(sample, Response)].  A hook exception must not kill
        the batcher thread (every future is already resolved) — warn once
        per hook and keep serving."""
        import warnings

        with self._lock:
            hooks = list(self._response_hooks.get(model, ()))
        for hook in hooks:
            for sample, resp in pairs:
                try:
                    hook(sample, resp)
                except Exception as e:
                    if id(hook) not in self._hook_warned:
                        self._hook_warned.add(id(hook))
                        warnings.warn(
                            f"response hook {hook!r} for {model!r} "
                            f"raised {type(e).__name__}: {e} (hook "
                            f"errors are reported once and ignored)")
                    break

    def resilience(self, model: str) -> Optional[ResilienceManager]:
        """The model's resilience control plane (None when the server
        was built without a ResilienceConfig) — the drill's and tests'
        observability handle for breakers/events."""
        return self._lane(model).resil

    def autoscaler(self, model: str) -> Optional[Autoscaler]:
        """The model's autoscaler (None when the server was built
        without an AutoscaleConfig) — the drill's and tests'
        observability handle for scale events/accounting."""
        return self._lane(model).auto

    def _lane(self, model: str) -> _Lane:
        with self._lock:
            lane = self._lanes.get(model)
        if lane is None:
            # registry lookup raises ModelNotLoaded with the loaded names
            self.registry.get(model)
            raise ServerClosed(f"model {model!r} has no serving lane")
        return lane

    # ------------------------------------------------------------- batching
    def _run_batch(self, lane: _Lane, replica_idx: int,
                   batch: List[_Request]) -> None:
        """Scheduler run callback: the batch a replica worker popped the
        moment it freed.  Captures (runner, generation) atomically so a
        concurrent reload() never mixes params inside one batch, and
        never raises — every future is resolved here, rejections
        included."""
        lm = lane.model
        mgr = lane.resil
        runner, generation = lm.replica_snapshot(replica_idx)
        with span("serve.assemble", model=lm.name,
                  replica=replica_idx) as sp:
            now = now_s()
            live: List[_Request] = []
            for r in batch:
                r.t_pop = now
                if r.deadline is not None and now > r.deadline:
                    lm.stats.bump("rejected_deadline")
                    if mgr is not None:
                        mgr.count_deadline_drop(
                            "assembly", (now - r.deadline) * 1e3,
                            replica=replica_idx)
                    r.future.set_exception(DeadlineExceeded(
                        f"deadline passed "
                        f"{round((now - r.deadline) * 1e3, 2)}"
                        f" ms before batch launch"))
                else:
                    live.append(r)
            sp.set(batch=len(batch), live=len(live),
                   queued=lane.sched.queued_total())
        if not live:
            return
        bucket = pick_bucket(len(live), runner.buckets)
        x = pad_to_bucket(
            np.stack([r.sample for r in live]).astype(np.float32), bucket)
        queued, inflight = lane.sched.depth(replica_idx)
        lm.stats.observe_replica(replica_idx, queued, inflight,
                                 dispatched=1)
        inject_err, spike_s = (mgr.on_dispatch(replica_idx)
                               if mgr is not None else (False, 0.0))
        t_launch = now_s()
        try:
            with span("serve.device", model=lm.name, bucket=bucket,
                      live=len(live), replica=replica_idx):
                if spike_s > 0:
                    # injected latency fault: the breaker sees a slow
                    # SUCCESS (device_ms includes the spike)
                    time.sleep(spike_s)
                if inject_err:
                    raise ServingError(
                        f"injected fault on replica {replica_idx} "
                        f"(ServeFaultPlan)")
                out = runner.forward_padded(x)
        except Exception as e:
            if mgr is not None:
                mgr.record_error(replica_idx)
                if not lane.stopping:
                    # exactly-once recovery: redispatch the failed
                    # requests onto healthy replicas (bounded retries);
                    # futures resolve only on delivery or final failure
                    retry = [r for r in live
                             if r.retries < mgr.cfg.max_retries]
                    for r in retry:
                        r.retries += 1
                    if retry:
                        try:
                            lane.sched.requeue(retry,
                                               exclude=replica_idx)
                            mgr.count_retried(len(retry))
                            # identity filter: _Request's dataclass
                            # __eq__ would compare sample arrays
                            kept = {id(r) for r in retry}
                            live = [r for r in live
                                    if id(r) not in kept]
                        except SchedulerClosed:
                            pass    # fall through: fail them below
            lm.stats.bump("failed", len(live))
            for r in live:
                r.future.set_exception(
                    ServingError(f"model {lm.name!r} forward failed: {e}"))
            return
        if mgr is not None:
            mgr.record_success(replica_idx)
        t_done = now_s()
        device_ms = (t_done - t_launch) * 1e3
        lm.stats.observe_batch(len(live), bucket)
        delivered = []
        with span("serve.respond", model=lm.name, bucket=bucket,
                  live=len(live)) as sp:
            for i, r in enumerate(live):
                total_ms = (t_done - r.t_submit) * 1e3
                queue_wait_ms = (r.t_pop - r.t_submit) * 1e3
                assembly_ms = (t_launch - r.t_pop) * 1e3
                lm.stats.observe_request(queue_wait_ms, assembly_ms,
                                         device_ms, total_ms)
                resp = Response(
                    probs=out[i], model=lm.name, generation=generation,
                    bucket=bucket, batch_live=len(live),
                    queue_wait_ms=round(queue_wait_ms, 4),
                    assembly_ms=round(assembly_ms, 4),
                    device_ms=round(device_ms, 4),
                    total_ms=round(total_ms, 4),
                    replica=replica_idx,
                    priority=r.priority)
                if mgr is not None:
                    mgr.observe_total(r.priority, total_ms)
                r.future.set_result(resp)
                delivered.append((r.sample, resp))
            sp.set(completed=lm.stats.value("completed"),
                   batches=lm.stats.value("batches"))
        self._fire_response_hooks(lm.name, delivered)

    # -------------------------------------------------------------- observe
    def stats(self) -> Dict[str, object]:
        """JSON-ready snapshot: per-model serving counters/latency
        histograms (stats.py) + live queue depths + a per-replica
        breakdown + the batching config."""
        per_model = self.registry.stats()
        with self._lock:
            lanes = dict(self._lanes)
        for name, lane in lanes.items():
            if name not in per_model:
                continue
            per_model[name]["queued_now"] = lane.sched.queued_total()
            per_model[name]["model_type"] = lane.model_type
            breakdown = lane.model.stats.replica_breakdown()
            for i, (queued, inflight) in enumerate(lane.sched.depths()):
                entry = breakdown.setdefault(
                    str(i), {"queued_max": 0, "inflight_max": 0,
                             "dispatches": 0})
                entry["queued_now"] = queued
                entry["inflight_now"] = inflight
            per_model[name]["replicas"] = breakdown
            if lane.resil is not None:
                per_model[name]["resilience"] = lane.resil.snapshot()
            if lane.auto is not None:
                per_model[name]["autoscale"] = lane.auto.snapshot()
        out: Dict[str, object] = {
            "models": per_model,
            "config": {"max_batch": self.config.max_batch,
                       "max_wait_ms": self.config.max_wait_ms,
                       "queue_depth": self.config.queue_depth,
                       "min_fill": self.config.min_fill,
                       "default_deadline_ms":
                           self.config.default_deadline_ms,
                       "resilience": self.config.resilience is not None,
                       "autoscale": self.config.autoscale is not None},
            "accepting": self._accepting}
        if self._placer is not None:
            out["placement"] = self._placer.describe()
        return out
