"""Bucketed batch shapes: the fixed set of batch sizes a served model
compiles for.

jit specializes per input shape, so serving raw coalesced batch sizes
(1..max_batch, whatever arrival timing produced) would compile up to
max_batch programs on demand, each a multi-second stall mid-traffic.
Instead every assembled micro-batch is zero-padded up to the smallest
bucket that holds it; the bucket set is warmed (pre-compiled) at model
load, so steady-state traffic never compiles.  Padding rows are sliced
off before responses are resolved; the padding is arithmetically exact —
per-sample rows of conv/pool/dense/softmax nets do not see their batch
neighbors (pinned bitwise by tests/test_serving.py).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """Default bucket ladder: powers of two up to `max_batch`, plus
    `max_batch` itself — log2(max_batch) programs bound the compile
    count while keeping padding waste under 2x at every size."""
    mb = int(max_batch)
    if mb < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    sizes = []
    b = 1
    while b < mb:
        sizes.append(b)
        b *= 2
    sizes.append(mb)
    return tuple(sizes)


def validate_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Sorted, deduplicated, all >= 1; the smallest bucket must be able
    to hold a single request (any positive smallest bucket can — padding
    fills the rest)."""
    bs = sorted({int(b) for b in buckets})
    if not bs or bs[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets!r}")
    return tuple(bs)


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket holding `n` requests."""
    for b in buckets:
        if b >= n:
            return int(b)
    raise ValueError(
        f"batch of {n} exceeds the largest bucket {max(buckets)}; the "
        f"batcher must cap assembly at max(buckets)")


def pad_to_bucket(x: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad a (k, ...) stack up to (bucket, ...).  Zeros, not row
    repeats: repeated rows would be live data if a slicing bug ever
    leaked a padding row, while zero rows fail loudly in parity tests."""
    k = len(x)
    if k > bucket:
        raise ValueError(f"batch of {k} does not fit bucket {bucket}")
    if k == bucket:
        return x
    pad = np.zeros((bucket - k,) + x.shape[1:], dtype=x.dtype)
    return np.concatenate([x, pad])
