"""Serving-layer error taxonomy.

The reference stack has no online-serving analogue (Caffe's Classifier
stops at offline batch scoring); the shape here follows the HTTP serving
convention TensorFlow-Serving popularized: admission failures and
deadline misses are REJECTIONS with a status code the caller can map to
503/504, distinct from programming errors (which stay ValueError/
TypeError) and from model lookup misses (404-style).
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base of every rejection the server issues; `status` carries the
    HTTP-style code a network front-end would map it to."""

    status = 500


class ServerOverloaded(ServingError):
    """Admission control: the model's request queue is at `queue_depth`.
    Raised synchronously by submit() — the 503 path.  Callers either
    back off or resubmit with `wait=True` for blocking admission."""

    status = 503


class ServerClosed(ServingError):
    """Submitted after shutdown began, or the request was still queued
    when a non-draining close() flushed it."""

    status = 503


class RequestShed(ServerOverloaded):
    """SLO-aware admission (serving/resilience.py): a batch-class
    request rejected while the server protects interactive latency —
    queue past the shed fraction, or the interactive EWMA over its SLO.
    Subclasses ServerOverloaded so existing 503 back-off handlers catch
    it unchanged; the distinct type lets loadgen/stats attribute sheds
    exactly (`rejected_shed` vs `rejected_overload`)."""

    status = 503


class DeadlineExceeded(ServingError):
    """The request's deadline passed before its batch launched — the 504
    path.  Checked at batch assembly, so an expired request never spends
    device time."""

    status = 504


class ModelNotLoaded(ServingError):
    """No model under that name in the registry (404 path)."""

    status = 404
