"""Caffe-compatible HDF5 weight and solver-state files.

The reference supports two snapshot wire formats (SolverParameter
snapshot_format, caffe.proto:222-226): BINARYPROTO (.caffemodel — see
binaryproto.py) and HDF5.  This module mirrors the HDF5 layouts exactly:

- Weights file (reference: Net::ToHDF5, net.cpp:920+ and
  Net::CopyTrainedLayersFromHDF5, net.cpp:860-908): root group "data"
  containing one subgroup per layer, each with float datasets named
  "0", "1", ... — one per param blob.
- Solver state file (reference: SGDSolver::SnapshotSolverStateToHDF5 /
  RestoreSolverStateFromHDF5, sgd_solver.cpp:278-330): scalar int datasets
  "iter" and "current_step", string dataset "learned_net", and a group
  "history" with datasets "0".."n-1".  Multi-slot solvers (Adam et al.)
  append extra slots after the first n entries, matching the reference's
  history_ layout (adam_solver.cpp grows history_ to 2n).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

try:
    import h5py

    HAVE_H5PY = True
except ImportError:  # pragma: no cover - h5py is in the base image
    HAVE_H5PY = False


def _require_h5py() -> None:
    if not HAVE_H5PY:
        raise RuntimeError("h5py is required for HDF5 snapshot support")


# ------------------------------------------------------------------- weights

def write_weights_hdf5(path: str,
                       weights: Dict[str, List[np.ndarray]]) -> None:
    """weights = {layer_name: [blob0, blob1, ...]} → Caffe .caffemodel.h5."""
    _require_h5py()
    with h5py.File(path, "w") as f:
        data = f.create_group("data")
        for layer_name, blobs in weights.items():
            g = data.create_group(layer_name)
            for j, blob in enumerate(blobs):
                g.create_dataset(str(j),
                                 data=np.asarray(blob, dtype=np.float32))


def read_weights_hdf5(path: str) -> Dict[str, List[np.ndarray]]:
    """Walks nested groups so slash-named layers (GoogLeNet's
    "inception_3a/1x1" etc.) round-trip: h5 treats '/' as group nesting, so
    such a layer's blobs live two levels deep."""
    _require_h5py()
    out: Dict[str, List[np.ndarray]] = {}

    def walk(group, prefix: str) -> None:
        blobs: Dict[int, np.ndarray] = {}
        for name in group:
            item = group[name]
            if isinstance(item, h5py.Group):
                walk(item, f"{prefix}/{name}" if prefix else name)
            else:
                blobs[int(name)] = np.asarray(item, dtype=np.float32)
        if blobs:
            out[prefix] = [blobs[i] for i in sorted(blobs)]

    with h5py.File(path, "r") as f:
        walk(f["data"], "")
    return out


# --------------------------------------------------------------- solver state

def write_solver_state_hdf5(path: str, *, iteration: int,
                            current_step: int = 0,
                            learned_net: str = "",
                            history: Sequence[np.ndarray] = ()) -> None:
    _require_h5py()
    with h5py.File(path, "w") as f:
        f.create_dataset("iter", data=np.int64(iteration))
        f.create_dataset("current_step", data=np.int64(current_step))
        if learned_net:
            f.create_dataset("learned_net", data=learned_net)
        g = f.create_group("history")
        for i, h in enumerate(history):
            g.create_dataset(str(i), data=np.asarray(h, dtype=np.float32))


def read_solver_state_hdf5(path: str) -> Dict[str, object]:
    _require_h5py()
    with h5py.File(path, "r") as f:
        out: Dict[str, object] = {
            "iter": int(np.asarray(f["iter"])),
            "current_step": int(np.asarray(f["current_step"]))
            if "current_step" in f else 0,
            "learned_net": "",
        }
        if "learned_net" in f:
            raw = f["learned_net"][()]
            out["learned_net"] = (raw.decode() if isinstance(raw, bytes)
                                  else str(raw))
        g = f["history"]
        hist = [None] * len(g)
        for ds_name in g:
            hist[int(ds_name)] = np.asarray(g[ds_name], dtype=np.float32)
        out["history"] = hist
    return out


# ------------------------------------------------- state dict <-> flat history

def flatten_state(state: Dict[str, Tuple[np.ndarray, ...]],
                  param_order: Sequence[str],
                  ) -> List[np.ndarray]:
    """Our solver state {param_key: (slot0, slot1, ...)} → the reference's
    flat history_ vector: slot-major, params in net order within a slot
    (matching adam_solver.cpp history_[i] / history_[i + n])."""
    n_slots = max((len(v) for v in state.values()), default=0)
    flat: List[np.ndarray] = []
    for slot in range(n_slots):
        for k in param_order:
            slots = state.get(k, ())
            if slot < len(slots):
                flat.append(np.asarray(slots[slot]))
    return flat


def unflatten_state(history: Sequence[np.ndarray],
                    param_order: Sequence[str], n_slots: int,
                    ) -> Dict[str, Tuple[np.ndarray, ...]]:
    n = len(param_order)
    if n_slots and len(history) != n * n_slots:
        raise ValueError(
            f"history length {len(history)} != {n} params x {n_slots} slots")
    out: Dict[str, List[np.ndarray]] = {k: [] for k in param_order}
    for slot in range(n_slots):
        for i, k in enumerate(param_order):
            out[k].append(np.asarray(history[slot * n + i]))
    return {k: tuple(v) for k, v in out.items()}
