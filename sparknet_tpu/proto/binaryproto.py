"""Protobuf *binary wire format* for weight interchange with the reference.

Covers the subset needed for (a) `.caffemodel` import/export — warm-starting
from nets trained by the reference and exporting back (reference:
Net::CopyTrainedLayersFromBinaryProto caffe/src/caffe/net.cpp:805-830,
bridge load/save ccaffe.cpp:261-269) — and (b) mean-image `.binaryproto`
files (reference: preprocessing/ComputeMean.scala:78-85 writing through
ccaffe, DataTransformer reading them).

Field numbers (reference: caffe/src/caffe/proto/caffe.proto):
  NetParameter: name=1, layers(V1)=2, layer=100
  LayerParameter: name=1, type=2, blobs=7
  V1LayerParameter: bottom=2, top=3, name=4, type(enum)=5, blobs=6
  BlobProto: num=1, channels=2, height=3, width=4, data=5 (packed float),
             diff=6, shape=7
  BlobShape: dim=1 (packed int64)
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

# ----------------------------------------------------------------- wire I/O


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise ValueError(f"truncated varint at byte {pos}")
        if shift > 63:
            # protobuf caps varints at 10 bytes; without this a corrupt
            # run of 0x80 continuation bytes grinds a growing bigint for
            # the whole buffer instead of failing in O(1)
            raise ValueError(f"varint longer than 10 bytes at {pos}")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message's bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 1:
            if pos + 8 > n:
                raise ValueError(f"truncated fixed64 field {field}")
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > n:
                # a short slice here would SILENTLY load a truncated blob
                # (e.g. an interrupted .caffemodel copy) — fail like the
                # reference's protobuf parser does
                raise ValueError(
                    f"truncated length-delimited field {field}: "
                    f"declares {ln} bytes, {n - pos} remain")
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            if pos + 4 > n:
                raise ValueError(f"truncated fixed32 field {field}")
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def _packed_floats(chunks: List[object], unpacked: List[object]) -> np.ndarray:
    parts = []
    for c in chunks:
        parts.append(np.frombuffer(c, dtype="<f4"))
    try:
        for u in unpacked:
            parts.append(np.asarray([struct.unpack("<f", u)[0]],
                                    dtype=np.float32))
    except (struct.error, TypeError) as e:
        raise ValueError(f"malformed float value in blob data: {e}") \
            from None
    if not parts:
        return np.zeros((0,), dtype=np.float32)
    return np.concatenate(parts)


# ---------------------------------------------------------------- BlobProto


def parse_blob(buf: bytes) -> np.ndarray:
    """BlobProto -> float32 array with its recorded shape (modern `shape` or
    legacy 4-d num/channels/height/width, blob.cpp:450-480 semantics)."""
    data_chunks: List[object] = []
    data_single: List[object] = []
    legacy = {}
    shape: Optional[List[int]] = None
    for field, wt, val in iter_fields(buf):
        if field == 5:
            # packed run (wt 2) or single fixed32 float (wt 5); a varint
            # or fixed64 here is a corrupt blob — routing it into the
            # float decode used to escape as TypeError/struct.error
            if wt == 2:
                data_chunks.append(val)
            elif wt == 5:
                data_single.append(val)
            else:
                raise ValueError(
                    f"BlobProto data (field 5) has wire type {wt}; "
                    f"expected packed (2) or fixed32 (5) floats")
        elif field == 7 and wt == 2:
            dims = []
            for f2, wt2, v2 in iter_fields(val):  # BlobShape
                if f2 == 1:
                    if wt2 == 2:
                        pos = 0
                        while pos < len(v2):
                            d, pos = _read_varint(v2, pos)
                            dims.append(d)
                    else:
                        dims.append(int(v2))
            shape = dims
        elif field in (1, 2, 3, 4) and wt == 0:
            legacy[field] = int(val)
    data = _packed_floats(data_chunks, data_single)
    if shape is None and legacy:
        shape = [legacy.get(1, 1), legacy.get(2, 1), legacy.get(3, 1),
                 legacy.get(4, 1)]
    if shape is not None:   # [] is a valid 0-d (scalar) shape
        data = data.reshape(shape)
    return data


def write_blob(arr: np.ndarray) -> bytes:
    """float32 array -> BlobProto bytes (modern shape + packed data)."""
    arr = np.asarray(arr, dtype=np.float32)
    out = bytearray()
    # shape (field 7): BlobShape with packed dims (field 1)
    dims = bytearray()
    packed = bytearray()
    for d in arr.shape:
        _write_varint(packed, int(d))
    _write_varint(dims, (1 << 3) | 2)
    _write_varint(dims, len(packed))
    dims += packed
    _write_varint(out, (7 << 3) | 2)
    _write_varint(out, len(dims))
    out += dims
    # data (field 5, packed floats)
    raw = arr.astype("<f4").tobytes()
    _write_varint(out, (5 << 3) | 2)
    _write_varint(out, len(raw))
    out += raw
    return bytes(out)


def read_mean_binaryproto(path: str) -> np.ndarray:
    """mean.binaryproto -> (C, H, W) float32 (squeezes the legacy num dim)."""
    with open(path, "rb") as f:
        arr = parse_blob(f.read())
    if arr.ndim == 4 and arr.shape[0] == 1:
        arr = arr[0]
    return arr


def write_mean_binaryproto(path: str, mean: np.ndarray) -> None:
    """(reference: ccaffe.cpp:83-97 write_mean_image — legacy 4-d blob)"""
    mean = np.asarray(mean, dtype=np.float32)
    if mean.ndim == 3:
        mean = mean[None]
    with open(path, "wb") as f:
        f.write(write_blob(mean))


# -------------------------------------------------------------- .caffemodel


def _layer_name_and_blobs(buf: bytes, name_field: int, blobs_field: int,
                          ) -> Tuple[str, List[np.ndarray]]:
    name = ""
    blobs: List[np.ndarray] = []
    for field, wt, val in iter_fields(buf):
        if field == name_field and wt == 2:
            name = val.decode("utf-8", "replace")
        elif field == blobs_field and wt == 2:
            blobs.append(parse_blob(val))
    return name, blobs


def read_caffemodel(path: str) -> Dict[str, List[np.ndarray]]:
    """Binary NetParameter -> {layer_name: [blob arrays]} — directly
    compatible with Net.set_weights / Solver.set_weights (the
    WeightCollection layout)."""
    with open(path, "rb") as f:
        buf = f.read()
    out: Dict[str, List[np.ndarray]] = {}
    for field, wt, val in iter_fields(buf):
        if field == 100 and wt == 2:          # modern LayerParameter
            name, blobs = _layer_name_and_blobs(val, 1, 7)
        elif field == 2 and wt == 2:          # V1LayerParameter
            name, blobs = _layer_name_and_blobs(val, 4, 6)
        else:
            continue
        if name and blobs:
            out[name] = blobs
    return out


def read_solverstate(path: str) -> Dict[str, object]:
    """Binary SolverState (.solverstate) -> {iter, learned_net, history,
    current_step} (reference: SGDSolver::RestoreSolverStateFromBinaryProto,
    sgd_solver.cpp:301-318; caffe.proto:245-250)."""
    with open(path, "rb") as f:
        buf = f.read()
    out: Dict[str, object] = {"iter": 0, "learned_net": "", "history": [],
                              "current_step": 0}
    history: List[np.ndarray] = []
    for field, wt, val in iter_fields(buf):
        if field == 1 and wt == 0:
            out["iter"] = int(val)
        elif field == 2 and wt == 2:
            out["learned_net"] = val.decode("utf-8", "replace")
        elif field == 3 and wt == 2:
            history.append(parse_blob(val))
        elif field == 4 and wt == 0:
            out["current_step"] = int(val)
    out["history"] = history
    return out


def write_solverstate(path: str, *, iteration: int, learned_net: str = "",
                      history: List[np.ndarray] = [],
                      current_step: int = 0) -> None:
    """(reference: SGDSolver::SnapshotSolverStateToBinaryProto,
    sgd_solver.cpp:242-258)"""
    out = bytearray()
    _write_varint(out, (1 << 3) | 0)
    _write_varint(out, int(iteration))
    if learned_net:
        enc = learned_net.encode()
        _write_varint(out, (2 << 3) | 2)
        _write_varint(out, len(enc))
        out += enc
    for h in history:
        bb = write_blob(h)
        _write_varint(out, (3 << 3) | 2)
        _write_varint(out, len(bb))
        out += bb
    _write_varint(out, (4 << 3) | 0)
    _write_varint(out, int(current_step))
    with open(path, "wb") as f:
        f.write(bytes(out))


def write_caffemodel(path: str, weights: Dict[str, List[np.ndarray]],
                     net_name: str = "sparknet_tpu") -> None:
    """{layer: [blobs]} -> binary NetParameter loadable by the reference's
    CopyTrainedLayersFromBinaryProto (layer name + blobs only, which is all
    that weight copying reads, net.cpp:805-830)."""
    out = bytearray()
    nb = net_name.encode()
    _write_varint(out, (1 << 3) | 2)
    _write_varint(out, len(nb))
    out += nb
    for name, blobs in weights.items():
        layer = bytearray()
        enc = name.encode()
        _write_varint(layer, (1 << 3) | 2)
        _write_varint(layer, len(enc))
        layer += enc
        for blob in blobs:
            bb = write_blob(blob)
            _write_varint(layer, (7 << 3) | 2)
            _write_varint(layer, len(bb))
            layer += bb
        _write_varint(out, (100 << 3) | 2)
        _write_varint(out, len(layer))
        out += layer
    with open(path, "wb") as f:
        f.write(bytes(out))
