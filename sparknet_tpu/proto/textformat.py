"""Pure-Python protobuf *text format* parser / serializer.

The reference parses prototxt through the C++ protobuf runtime reached over JNA
(reference: libccaffe/ccaffe.cpp:275-304, src/main/scala/libs/ProtoLoader.scala:9-29).
We need no generated bindings: prototxt is a simple self-describing text tree, so a
schema-less recursive-descent parser suffices.  Typed, defaulted access on top of the
raw tree lives in `caffe_pb.py`.

Grammar (informal):

    message  := field*
    field    := IDENT ':' scalar | IDENT '{' message '}' | IDENT '<' message '>'
    scalar   := STRING | NUMBER | BOOL | ENUM_IDENT

Repeated fields appear as repeated keys.  Comments run '#' to end of line.
"""

from __future__ import annotations

import re
from typing import Any, Iterator, List, Optional, Union


class Message:
    """Dynamic protobuf message: ordered multimap of field name -> values.

    Values are str/int/float/bool scalars, `Enum` tokens, or nested `Message`s.
    Field order is preserved for faithful re-serialization.
    """

    __slots__ = ("_fields",)

    def __init__(self) -> None:
        # name -> list of values (singular fields hold a 1-element list)
        self._fields: dict[str, list[Any]] = {}

    # -- construction -------------------------------------------------------
    def add(self, name: str, value: Any) -> None:
        self._fields.setdefault(name, []).append(value)

    def set(self, name: str, value: Any) -> None:
        self._fields[name] = [value]

    def set_list(self, name: str, values: List[Any]) -> None:
        self._fields[name] = list(values)

    def clear(self, name: str) -> None:
        self._fields.pop(name, None)

    # -- access -------------------------------------------------------------
    def get(self, name: str, default: Any = None) -> Any:
        vals = self._fields.get(name)
        if not vals:
            return default
        return vals[-1]  # proto3/proto2 semantics: last singular value wins

    def getlist(self, name: str) -> List[Any]:
        return list(self._fields.get(name, []))

    def has(self, name: str) -> bool:
        return bool(self._fields.get(name))

    def keys(self):
        return self._fields.keys()

    def items(self) -> Iterator[tuple]:
        for k, vals in self._fields.items():
            for v in vals:
                yield k, v

    def copy(self) -> "Message":
        m = Message()
        for k, vals in self._fields.items():
            m._fields[k] = [v.copy() if isinstance(v, Message) else v for v in vals]
        return m

    def __contains__(self, name: str) -> bool:
        return self.has(name)

    def __repr__(self) -> str:
        return f"Message({dict(self._fields)!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Message) and self._fields == other._fields


class Enum(str):
    """A bare-identifier scalar (enum value) — a str subtype so comparisons with
    string literals work, but serialized without quotes."""

    __slots__ = ()


_TOKEN_RE = re.compile(
    r"""
    (?P<space>\s+|\#[^\n]*)
  | (?P<brace>[{}<>])
  | (?P<punct>[\[\],;])
  | (?P<colon>:)
  | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<number>[-+]?(?:\.\d+|\d+\.?\d*)(?:[eE][-+]?\d+)?|[-+]?(?:inf(?:inity)?|nan)\b)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
    """,
    re.VERBOSE,
)

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "'": "'", "\\": "\\", "0": "\0"}


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    out, i = [], 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            out.append(_ESCAPES.get(body[i + 1], body[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _tokenize(text: str) -> Iterator[tuple[str, str]]:
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise ValueError(
                f"prototxt tokenize error at offset {pos}: {text[pos:pos+40]!r}"
            )
        pos = m.end()
        kind = m.lastgroup
        if kind != "space":
            yield kind, m.group()
    yield "eof", ""


class _Parser:
    # textformat nests shallowly (LayerParameter -> per-layer param ->
    # filler is ~4 deep; give 25x headroom); the cap turns a pathological
    # input's RecursionError into the same clean ValueError every other
    # malformed input gets.  It must stay well under Python's recursion
    # limit counted in FRAMES PER LEVEL — the colon-message syntax
    # (`a: { ... }`) recurses through _parse_scalar, 3 frames/level
    MAX_DEPTH = 100

    def __init__(self, text: str) -> None:
        self._toks = list(_tokenize(text))
        self._i = 0
        self._depth = 0

    def _peek(self) -> tuple[str, str]:
        return self._toks[self._i]

    def _next(self) -> tuple[str, str]:
        t = self._toks[self._i]
        self._i += 1
        return t

    def parse_message(self, terminator: Optional[str] = None) -> Message:
        self._depth += 1
        if self._depth > self.MAX_DEPTH:
            raise ValueError(
                f"message nesting exceeds {self.MAX_DEPTH} levels")
        try:
            return self._parse_message_body(terminator)
        finally:
            self._depth -= 1

    def _parse_message_body(self, terminator: Optional[str]) -> Message:
        msg = Message()
        while True:
            kind, tok = self._peek()
            if kind == "eof":
                if terminator is not None:
                    raise ValueError("unexpected EOF inside message")
                return msg
            if kind == "brace" and tok in ("}", ">"):
                if terminator is None or tok != terminator:
                    raise ValueError(f"unexpected {tok!r}")
                self._next()
                return msg
            if kind != "ident":
                raise ValueError(f"expected field name, got {tok!r}")
            name = self._next()[1]
            kind, tok = self._peek()
            if kind == "colon":
                self._next()
                if self._peek() == ("punct", "["):
                    for v in self._parse_bracket_list():
                        msg.add(name, v)
                else:
                    msg.add(name, self._parse_scalar())
            elif kind == "brace" and tok in ("{", "<"):
                self._next()
                msg.add(name, self.parse_message("}" if tok == "{" else ">"))
            else:
                raise ValueError(f"expected ':' or '{{' after {name!r}, got {tok!r}")
            # optional field separators (legal text format)
            while self._peek() == ("punct", ";") or self._peek() == ("punct", ","):
                self._next()

    def _parse_bracket_list(self) -> list:
        """`field: [v, v, ...]` — short repeated-field syntax."""
        self._next()  # consume '['
        vals: list = []
        if self._peek() == ("punct", "]"):
            self._next()
            return vals
        while True:
            vals.append(self._parse_scalar())
            kind, tok = self._next()
            if (kind, tok) == ("punct", "]"):
                return vals
            if (kind, tok) != ("punct", ","):
                raise ValueError(f"expected ',' or ']' in list, got {tok!r}")

    def _parse_scalar(self) -> Any:
        kind, tok = self._next()
        if kind == "string":
            # adjacent string literals concatenate (proto text format)
            parts = [_unquote(tok)]
            while self._peek()[0] == "string":
                parts.append(_unquote(self._next()[1]))
            return "".join(parts)
        if kind == "number":
            if re.fullmatch(r"[-+]?\d+", tok):
                return int(tok)
            return float(tok)
        if kind == "ident":
            if tok == "true":
                return True
            if tok == "false":
                return False
            return Enum(tok)
        if kind == "brace" and tok in ("{", "<"):
            # `field: { ... }` — colon before a message is legal text format
            return self.parse_message("}" if tok == "{" else ">")
        raise ValueError(f"bad scalar token {tok!r}")


def parse(text: str) -> Message:
    """Parse prototxt text into a `Message` tree."""
    return _Parser(text).parse_message()


def parse_file(path: str) -> Message:
    with open(path, "r") as f:
        return parse(f.read())


def _fmt_scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, Enum):
        return str(v)
    if isinstance(v, str):
        body = v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        return f'"{body}"'
    if isinstance(v, float):
        s = repr(v)
        return s
    return str(v)


def serialize(msg: Message, indent: int = 0) -> str:
    """Serialize a `Message` back to prototxt text (round-trips `parse`)."""
    pad = "  " * indent
    out: list[str] = []
    for name, value in msg.items():
        if isinstance(value, Message):
            out.append(f"{pad}{name} {{\n{serialize(value, indent + 1)}{pad}}}\n")
        else:
            out.append(f"{pad}{name}: {_fmt_scalar(value)}\n")
    return "".join(out)
