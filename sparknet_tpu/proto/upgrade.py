"""Legacy prototxt upgrades: V0/V1 NetParameter and old SolverParameter.

The reference engine transparently upgrades old model definitions on load
(reference: caffe/src/caffe/util/upgrade_proto.cpp, API at
caffe/include/caffe/util/upgrade_proto.hpp:11-68) and ships standalone
upgrade tools (caffe/tools/upgrade_net_proto_text.cpp,
upgrade_solver_proto_text.cpp).  Three generations exist:

* **V0** — `layers { layer { name type("conv"...) num_output ... } }`:
  a repeated `layers` *connection* holding a nested flat `layer` message
  (caffe.proto:1134,1139-1230); padding was a separate layer type folded
  into the following conv on upgrade (upgrade_proto.cpp UpgradeV0PaddingLayers).
* **V1** — `layers { name type: CONVOLUTION ... }`: repeated `layers` with an
  enum type and `blobs_lr`/`weight_decay` float lists instead of `param`
  specs (caffe.proto:1045-1135).
* **V2 (modern)** — `layer { name type: "Convolution" param {...} }`.

This module upgrades the dynamic `Message` tree in place-free style and is
invoked automatically by `caffe_pb.load_net_prototxt` /
`load_solver_prototxt`, mirroring `UpgradeNetAsNeeded` being called from
`ReadNetParamsFromTextFileOrDie` (upgrade_proto.cpp:937-960).
"""

from __future__ import annotations

from typing import List, Optional

from .textformat import Enum, Message

# V1LayerParameter.LayerType enum name -> modern type string
# (caffe.proto:1051-1095 enum; string names from upgrade_proto.cpp
# UpgradeV1LayerType).
V1_TYPE_TO_NAME = {
    "NONE": "",
    "ABSVAL": "AbsVal",
    "ACCURACY": "Accuracy",
    "ARGMAX": "ArgMax",
    "BNLL": "BNLL",
    "CONCAT": "Concat",
    "CONTRASTIVE_LOSS": "ContrastiveLoss",
    "CONVOLUTION": "Convolution",
    "DATA": "Data",
    "DECONVOLUTION": "Deconvolution",
    "DROPOUT": "Dropout",
    "DUMMY_DATA": "DummyData",
    "EUCLIDEAN_LOSS": "EuclideanLoss",
    "ELTWISE": "Eltwise",
    "EXP": "Exp",
    "FLATTEN": "Flatten",
    "HDF5_DATA": "HDF5Data",
    "HDF5_OUTPUT": "HDF5Output",
    "HINGE_LOSS": "HingeLoss",
    "IM2COL": "Im2col",
    "IMAGE_DATA": "ImageData",
    "INFOGAIN_LOSS": "InfogainLoss",
    "INNER_PRODUCT": "InnerProduct",
    "LRN": "LRN",
    "MEMORY_DATA": "MemoryData",
    "MULTINOMIAL_LOGISTIC_LOSS": "MultinomialLogisticLoss",
    "MVN": "MVN",
    "POOLING": "Pooling",
    "POWER": "Power",
    "RELU": "ReLU",
    "SIGMOID": "Sigmoid",
    "SIGMOID_CROSS_ENTROPY_LOSS": "SigmoidCrossEntropyLoss",
    "SILENCE": "Silence",
    "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "SPLIT": "Split",
    "SLICE": "Slice",
    "TANH": "TanH",
    "WINDOW_DATA": "WindowData",
    "THRESHOLD": "Threshold",
}

# V0 lowercase type string -> modern type string (upgrade_proto.cpp
# UpgradeV0LayerType, composed with the V1 table above).
V0_TYPE_TO_NAME = {
    "accuracy": "Accuracy",
    "bnll": "BNLL",
    "concat": "Concat",
    "conv": "Convolution",
    "data": "Data",
    "dropout": "Dropout",
    "euclidean_loss": "EuclideanLoss",
    "flatten": "Flatten",
    "hdf5_data": "HDF5Data",
    "hdf5_output": "HDF5Output",
    "im2col": "Im2col",
    "images": "ImageData",
    "infogain_loss": "InfogainLoss",
    "innerproduct": "InnerProduct",
    "lrn": "LRN",
    "multinomial_logistic_loss": "MultinomialLogisticLoss",
    "pool": "Pooling",
    "relu": "ReLU",
    "sigmoid": "Sigmoid",
    "softmax": "Softmax",
    "softmax_loss": "SoftmaxWithLoss",
    "split": "Split",
    "tanh": "TanH",
    "window_data": "WindowData",
}

# Fields that migrated out of DataParameter-family messages into
# TransformationParameter (upgrade_proto.cpp UpgradeNetDataTransformation).
_TRANSFORM_FIELDS = ("scale", "mean_file", "crop_size", "mirror")
_DATA_PARAM_MSGS = ("data_param", "image_data_param", "window_data_param")


def _is_v0(net: Message) -> bool:
    return any(isinstance(m, Message) and m.has("layer")
               for m in net.getlist("layers"))


def net_needs_upgrade(net: Message) -> bool:
    """Mirror of NetNeedsUpgrade (upgrade_proto.cpp:14-17): any legacy
    `layers` field, or transformation fields still inside data params."""
    if net.has("layers"):
        return True
    for layer in net.getlist("layer"):
        for pm in _DATA_PARAM_MSGS:
            sub = layer.get(pm)
            if isinstance(sub, Message) and any(
                    sub.has(f) for f in _TRANSFORM_FIELDS):
                return True
    return False


def solver_needs_upgrade(solver: Message) -> bool:
    return solver.has("solver_type")


def _move_fields(src: Message, dst: Message, mapping: dict) -> None:
    for old, new in mapping.items():
        for v in src.getlist(old):
            dst.add(new, v)
        src.clear(old)


def _upgrade_v0_layer(conn: Message, pad: Optional[int]) -> Message:
    """One V0 connection {layer{...} bottom top} -> modern layer message.
    `pad` is carried in from a preceding V0 "padding" layer, if any
    (upgrade_proto.cpp UpgradeV0PaddingLayers)."""
    v0 = conn.get("layer")
    if not isinstance(v0, Message):
        raise ValueError(
            "V0 net mixes connection styles: `layers` entry without a "
            "nested `layer` message")
    out = Message()
    if v0.has("name"):
        out.set("name", v0.get("name"))
    old_type = str(v0.get("type", ""))
    if old_type not in V0_TYPE_TO_NAME:
        raise ValueError(f"unknown V0 layer type {old_type!r}")
    new_type = V0_TYPE_TO_NAME[old_type]
    out.set("type", new_type)
    for b in conn.getlist("bottom"):
        out.add("bottom", b)
    for t in conn.getlist("top"):
        out.add("top", t)

    if new_type in ("Convolution", "InnerProduct"):
        pm = Message()
        _move_fields(v0, pm, {
            "num_output": "num_output", "biasterm": "bias_term",
            "weight_filler": "weight_filler", "bias_filler": "bias_filler"})
        if new_type == "Convolution":
            _move_fields(v0, pm, {"pad": "pad", "kernelsize": "kernel_size",
                                  "group": "group", "stride": "stride"})
            if pad is not None:
                pm.set("pad", pad)
        out.set("convolution_param" if new_type == "Convolution"
                else "inner_product_param", pm)
    elif new_type == "Pooling":
        pm = Message()
        if v0.has("pool"):
            pm.set("pool", Enum(str(v0.get("pool"))))
        _move_fields(v0, pm, {"kernelsize": "kernel_size", "stride": "stride",
                              "pad": "pad"})
        out.set("pooling_param", pm)
    elif new_type == "Dropout":
        pm = Message()
        _move_fields(v0, pm, {"dropout_ratio": "dropout_ratio"})
        out.set("dropout_param", pm)
    elif new_type == "LRN":
        pm = Message()
        _move_fields(v0, pm, {"local_size": "local_size", "alpha": "alpha",
                              "beta": "beta", "k": "k"})
        out.set("lrn_param", pm)
    elif new_type == "Concat":
        pm = Message()
        _move_fields(v0, pm, {"concat_dim": "concat_dim"})
        out.set("concat_param", pm)
    elif new_type in ("Data", "ImageData", "HDF5Data", "WindowData"):
        pm = Message()
        _move_fields(v0, pm, {"source": "source", "batchsize": "batch_size",
                              "rand_skip": "rand_skip"})
        out.set({"Data": "data_param", "ImageData": "image_data_param",
                 "HDF5Data": "hdf5_data_param",
                 "WindowData": "window_data_param"}[new_type], pm)
        tp = Message()
        _move_fields(v0, tp, {"scale": "scale", "meanfile": "mean_file",
                              "cropsize": "crop_size", "mirror": "mirror"})
        if list(tp.keys()):
            out.set("transform_param", tp)

    for b in v0.getlist("blobs"):
        out.add("blobs", b)
    _v1_param_specs(v0, out)
    return out


def _v1_param_specs(src: Message, out: Message) -> None:
    """blobs_lr / weight_decay / param-name lists -> modern `param` specs
    (upgrade_proto.cpp UpgradeV1LayerParameter param handling)."""
    names = [str(v) for v in src.getlist("param")]
    lrs = [float(v) for v in src.getlist("blobs_lr")]
    decays = [float(v) for v in src.getlist("weight_decay")]
    n = max(len(names), len(lrs), len(decays))
    for i in range(n):
        spec = Message()
        if i < len(names) and names[i]:
            spec.set("name", names[i])
        if i < len(lrs):
            spec.set("lr_mult", lrs[i])
        if i < len(decays):
            spec.set("decay_mult", decays[i])
        out.add("param", spec)


def upgrade_v0_net(net: Message) -> Message:
    """V0 -> modern, including padding-layer folding: a V0 "padding" layer's
    pad value moves into the consuming conv and the padding layer vanishes,
    with blob names rewired (upgrade_proto.cpp UpgradeV0PaddingLayers)."""
    out = Message()
    for k, v in net.items():
        if k != "layers":
            out.add(k, v)
    # blob produced by a padding layer -> (source blob, pad value)
    pad_tops: dict = {}
    for conn in net.getlist("layers"):
        v0 = conn.get("layer")
        if v0 is not None and str(v0.get("type", "")) == "padding":
            src = str(conn.getlist("bottom")[0])
            top = str(conn.getlist("top")[0])
            pad_tops[top] = (src, int(v0.get("pad", 0)))
            continue
        pad = None
        bottoms = [str(b) for b in conn.getlist("bottom")]
        if any(b in pad_tops for b in bottoms):
            v0t = str(conn.get("layer").get("type", ""))
            if v0t != "conv":
                # the reference CHECKs padding feeds only convs
                # (upgrade_proto.cpp UpgradeV0PaddingLayers)
                raise ValueError(
                    f"V0 padding layer output consumed by non-conv layer "
                    f"type {v0t!r}")
            conn = conn.copy()
            rewired = []
            for b in bottoms:
                if b in pad_tops:
                    src, pad = pad_tops[b]
                    rewired.append(src)
                else:
                    rewired.append(b)
            conn.set_list("bottom", rewired)
        out.add("layer", _upgrade_v0_layer(conn, pad))
    return out


def upgrade_v1_layer(v1: Message) -> Message:
    out = Message()
    enum_name = str(v1.get("type", "NONE"))
    if enum_name not in V1_TYPE_TO_NAME:
        raise ValueError(f"unknown V1 layer type {enum_name!r}")
    passthrough_skip = {"type", "blobs_lr", "weight_decay", "param",
                        "blob_share_mode", "layer"}
    if v1.has("name"):
        out.set("name", v1.get("name"))
        passthrough_skip.add("name")
    out.set("type", V1_TYPE_TO_NAME[enum_name])
    for k, v in v1.items():
        if k not in passthrough_skip:
            out.add(k, v)
    _v1_param_specs(v1, out)
    shares = [str(v) for v in v1.getlist("blob_share_mode")]
    specs = out.getlist("param")
    for i, mode in enumerate(shares):
        if i < len(specs):
            specs[i].set("share_mode", Enum(mode))
    return out


def upgrade_v1_net(net: Message) -> Message:
    out = Message()
    for k, v in net.items():
        if k != "layers":
            out.add(k, v)
    for v1 in net.getlist("layers"):
        out.add("layer", upgrade_v1_layer(v1))
    return out


def upgrade_net_data_transformation(net: Message) -> None:
    """Move scale/mean_file/crop_size/mirror out of data params into
    transform_param, in place (upgrade_proto.cpp
    UpgradeNetDataTransformation)."""
    for layer in net.getlist("layer"):
        for pm_name in _DATA_PARAM_MSGS:
            pm = layer.get(pm_name)
            if not isinstance(pm, Message):
                continue
            moved = {f: pm.get(f) for f in _TRANSFORM_FIELDS if pm.has(f)}
            if not moved:
                continue
            tp = layer.get("transform_param")
            if not isinstance(tp, Message):
                tp = Message()
                layer.set("transform_param", tp)
            for f, v in moved.items():
                if not tp.has(f):
                    tp.set(f, v)
                pm.clear(f)


def upgrade_net_as_needed(net: Message) -> Message:
    """Full upgrade chain (upgrade_proto.cpp UpgradeNetAsNeeded:
    V0 -> V1 -> data-transformation -> V2)."""
    if net.has("layers"):
        net = upgrade_v0_net(net) if _is_v0(net) else upgrade_v1_net(net)
    upgrade_net_data_transformation(net)
    return net


def upgrade_solver_as_needed(solver: Message) -> Message:
    """Old enum `solver_type` -> string `type` (upgrade_proto.cpp
    UpgradeSolverType)."""
    if not solver.has("solver_type"):
        return solver
    table = {"SGD": "SGD", "NESTEROV": "Nesterov", "ADAGRAD": "AdaGrad",
             "RMSPROP": "RMSProp", "ADADELTA": "AdaDelta", "ADAM": "Adam",
             "0": "SGD", "1": "Nesterov", "2": "AdaGrad", "3": "RMSProp",
             "4": "AdaDelta", "5": "Adam"}
    key = str(solver.get("solver_type"))
    if key not in table:
        raise ValueError(f"unknown solver_type {key!r}")
    if not solver.has("type"):
        solver.set("type", table[key])
    solver.clear("solver_type")
    return solver
