"""Generic binary protobuf (wire format) <-> textformat.Message codec,
driven by the generated schema tables (binary_schema.py).

This is the binary sibling of textformat.py: where text protos are
self-describing, the wire format needs field numbers and scalar kinds —
exactly what the reference's generated C++ classes embed
(caffe/src/caffe/proto/caffe.proto; used by
tools/upgrade_net_proto_binary.cpp via ReadNetParamsFromBinaryFileOrDie,
upgrade_proto.cpp:~1100).  Decoding lands in the same dynamic `Message`
tree the text parser builds, so every downstream consumer — typed views,
the V0/V1 upgrade chain, the serializer — works unchanged on binary
inputs.

Contract notes:
- decode: unknown field NUMBERS are skipped and reported through the
  optional `unknown` collector (proto2 semantics — old readers skip new
  fields); malformed wire data raises ValueError (callers that read
  files wrap it with the filename, per the repo parser contract).
- encode: unknown field NAMES raise ValueError — silently dropping a
  misspelled field from a write would lose data.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from .binary_schema import ENUMS, MESSAGES
from .binaryproto import _write_varint, iter_fields
from .textformat import Enum, Message

# number -> (name, kind, repeated, packed), per message
_BY_NUMBER = {
    msg: {num: (name, kind, rep, packed)
          for name, (num, kind, rep, packed) in fields.items()}
    for msg, fields in MESSAGES.items()
}
# enum: qualified name -> value->NAME
_ENUM_NAMES = {en: {v: k for k, v in vals.items()}
               for en, vals in ENUMS.items()}

_VARINT_KINDS = {"int32", "int64", "uint32", "uint64", "bool"}
_SIGNED_KINDS = {"int32", "int64"}


def _to_signed(val: int) -> int:
    """Proto2 int32/int64 negative values arrive as 10-byte varints."""
    return val - (1 << 64) if val >= (1 << 63) else val


def _decode_scalar(kind: str, wt: int, val, unknown) -> object:
    if kind in _VARINT_KINDS:
        if wt != 0:
            raise ValueError(f"wire type {wt} for varint kind {kind}")
        if kind == "bool":
            return bool(val)
        return _to_signed(val) if kind in _SIGNED_KINDS else val
    if kind == "float":
        if wt != 5:
            raise ValueError(f"wire type {wt} for float")
        try:
            return struct.unpack("<f", val)[0]
        except struct.error as e:
            raise ValueError(f"malformed float value: {e}") from None
    if kind == "double":
        if wt != 1:
            raise ValueError(f"wire type {wt} for double")
        try:
            return struct.unpack("<d", val)[0]
        except struct.error as e:
            raise ValueError(f"malformed double value: {e}") from None
    if kind == "string":
        if wt != 2:
            raise ValueError(f"wire type {wt} for string")
        try:
            return val.decode("utf-8")
        except UnicodeDecodeError as e:
            raise ValueError(f"invalid utf-8 in string field: {e}") from None
    if kind == "bytes":
        if wt != 2:
            raise ValueError(f"wire type {wt} for bytes")
        return val
    if kind.startswith("enum:"):
        if wt != 0:
            raise ValueError(f"wire type {wt} for enum")
        names = _ENUM_NAMES[kind[5:]]
        if val not in names:
            raise ValueError(f"unknown value {val} for enum {kind[5:]}")
        return Enum(names[val])
    raise ValueError(f"unhandled kind {kind}")


def _decode_packed(kind: str, buf: bytes) -> List[object]:
    out: List[object] = []
    if kind in _VARINT_KINDS:
        pos, n = 0, len(buf)
        from .binaryproto import _read_varint
        while pos < n:
            v, pos = _read_varint(buf, pos)
            out.append(bool(v) if kind == "bool"
                       else (_to_signed(v) if kind in _SIGNED_KINDS else v))
        return out
    if kind == "float":
        if len(buf) % 4:
            raise ValueError("packed float run not a multiple of 4 bytes")
        # numpy bulk conversion: real .caffemodel blobs carry tens of
        # millions of packed floats (same fast form as binaryproto's
        # _packed_floats)
        import numpy as np
        return np.frombuffer(buf, dtype="<f4").astype(float).tolist()
    if kind == "double":
        if len(buf) % 8:
            raise ValueError("packed double run not a multiple of 8 bytes")
        import numpy as np
        return np.frombuffer(buf, dtype="<f8").tolist()
    if kind.startswith("enum:"):
        names = _ENUM_NAMES[kind[5:]]
        pos, n = 0, len(buf)
        from .binaryproto import _read_varint
        while pos < n:
            v, pos = _read_varint(buf, pos)
            if v not in names:
                raise ValueError(f"unknown value {v} for enum {kind[5:]}")
            out.append(Enum(names[v]))
        return out
    raise ValueError(f"kind {kind} cannot be packed")


def decode_message(buf: bytes, msg_name: str,
                   unknown: Optional[List[Tuple[str, int]]] = None
                   ) -> Message:
    """Wire bytes -> dynamic Message (field names from the schema)."""
    if msg_name not in _BY_NUMBER:
        raise ValueError(f"unknown message type {msg_name!r}")
    table = _BY_NUMBER[msg_name]
    out = Message()
    for num, wt, val in iter_fields(buf):
        ent = table.get(num)
        if ent is None:
            if unknown is not None:
                unknown.append((msg_name, num))
            continue
        name, kind, repeated, _packed = ent
        if kind.startswith("msg:"):
            if wt != 2:
                raise ValueError(f"wire type {wt} for submessage {name}")
            out.add(name, decode_message(val, kind[4:], unknown))
        elif wt == 2 and kind not in ("string", "bytes"):
            # packed run (proto2 decoders accept packed even when the
            # schema says unpacked, and vice versa); bulk-extend — one
            # add() per element is quadratic-feeling on 60M-float blobs
            out.set_list(name, out.getlist(name) + _decode_packed(kind,
                                                                  val))
        else:
            out.add(name, _decode_scalar(kind, wt, val, unknown))
    return out


def _encode_scalar(out: bytearray, num: int, kind: str, v) -> None:
    if kind in _VARINT_KINDS:
        _write_varint(out, num << 3 | 0)
        _write_varint(out, _varint_value(kind, v))
    elif kind == "float":
        _write_varint(out, num << 3 | 5)
        out += struct.pack("<f", float(v))
    elif kind == "double":
        _write_varint(out, num << 3 | 1)
        out += struct.pack("<d", float(v))
    elif kind == "string":
        data = str(v).encode("utf-8")
        _write_varint(out, num << 3 | 2)
        _write_varint(out, len(data))
        out += data
    elif kind == "bytes":
        data = v if isinstance(v, (bytes, bytearray)) else \
            str(v).encode("utf-8")
        _write_varint(out, num << 3 | 2)
        _write_varint(out, len(data))
        out += bytes(data)
    elif kind.startswith("enum:"):
        _write_varint(out, num << 3 | 0)
        _write_varint(out, _enum_value(kind[5:], v))
    else:
        raise ValueError(f"unhandled kind {kind}")


def _varint_value(kind: str, v) -> int:
    if kind == "bool":
        if isinstance(v, str):
            return 1 if v.lower() == "true" else 0
        return 1 if v else 0
    iv = int(v)
    return iv & ((1 << 64) - 1) if iv < 0 else iv


def _enum_value(enum_name: str, v) -> int:
    vals = ENUMS[enum_name]
    s = str(v)
    if s in vals:
        return vals[s]
    try:
        iv = int(s)
    except ValueError:
        raise ValueError(
            f"unknown name {s!r} for enum {enum_name}") from None
    if iv not in _ENUM_NAMES[enum_name]:
        raise ValueError(f"unknown value {iv} for enum {enum_name}")
    return iv


def encode_message(msg: Message, msg_name: str) -> bytes:
    """Dynamic Message -> wire bytes, fields in schema (number) order."""
    if msg_name not in MESSAGES:
        raise ValueError(f"unknown message type {msg_name!r}")
    table = MESSAGES[msg_name]
    known = sorted(table.items(), key=lambda kv: kv[1][0])
    stray = [k for k in msg.keys() if k not in table and msg.has(k)]
    if stray:
        raise ValueError(
            f"field(s) {stray} not in the {msg_name} schema — encoding "
            f"would silently drop them")
    out = bytearray()
    for name, (num, kind, _rep, packed) in known:
        vals = msg.getlist(name)
        if not vals:
            continue
        if kind.startswith("msg:"):
            for v in vals:
                if not isinstance(v, Message):
                    raise ValueError(
                        f"{msg_name}.{name}: expected Message, "
                        f"got {type(v).__name__}")
                sub = encode_message(v, kind[4:])
                _write_varint(out, num << 3 | 2)
                _write_varint(out, len(sub))
                out += sub
        elif packed:
            if kind in ("float", "double"):
                import numpy as np
                # np.asarray converts float/int/numeric-string elements
                # in bulk — no per-element Python loop on 60M-float blobs
                body = np.asarray(
                    vals, dtype="<f4" if kind == "float" else "<f8"
                ).tobytes()
            else:
                b = bytearray()
                for v in vals:
                    if kind in _VARINT_KINDS:
                        _write_varint(b, _varint_value(kind, v))
                    else:  # pragma: no cover - schema has no packed enums
                        _write_varint(b, _enum_value(kind[5:], v))
                body = bytes(b)
            _write_varint(out, num << 3 | 2)
            _write_varint(out, len(body))
            out += body
        else:
            for v in vals:
                _encode_scalar(out, num, kind, v)
    return bytes(out)
