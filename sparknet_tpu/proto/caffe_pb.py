"""Typed, defaulted views over parsed prototxt `Message` trees.

Field names / defaults mirror the reference schema
(reference: caffe/src/caffe/proto/caffe.proto) so that the bundled model and
solver prototxts (cifar10_quick/full, LeNet, AlexNet, CaffeNet, GoogLeNet)
parse with identical semantics.  Only the subset actually consumed by the
framework is given a typed view; everything else stays reachable through the
raw `Message`.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from .textformat import Enum, Message, parse, parse_file, serialize


class View:
    """Base: wraps a raw Message; subclasses define DEFAULTS for scalar fields."""

    DEFAULTS: dict[str, Any] = {}

    def __init__(self, msg: Optional[Message] = None) -> None:
        self.msg = msg if msg is not None else Message()

    def __getattr__(self, name: str):
        # Only called when normal lookup fails -> field access on the message.
        if name.startswith("_") or name == "msg":
            raise AttributeError(name)
        defaults = type(self).DEFAULTS
        if name in defaults:
            v = self.msg.get(name, defaults[name])
            d = defaults[name]
            if isinstance(d, float) and v is not None and not isinstance(v, bool):
                return float(v)
            if isinstance(d, int) and not isinstance(d, bool) and v is not None \
                    and not isinstance(v, bool) and not isinstance(v, str):
                return int(v)
            return v
        return self.msg.get(name)

    def has(self, name: str) -> bool:
        return self.msg.has(name)

    def getlist(self, name: str) -> List[Any]:
        return self.msg.getlist(name)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.msg!r})"


# ---------------------------------------------------------------------------
# Fillers (caffe.proto:43-62)
# ---------------------------------------------------------------------------

class FillerParameter(View):
    DEFAULTS = dict(type="constant", value=0.0, min=0.0, max=1.0, mean=0.0,
                    std=1.0, sparse=-1, variance_norm="FAN_IN")


# ---------------------------------------------------------------------------
# Per-layer parameter messages
# ---------------------------------------------------------------------------

def _resolve_hw(msg: Message, name: str, default: int) -> tuple:
    """Resolve a spatial size from repeated `name` and/or `<stem>_h`/`<stem>_w`
    (the 2-D alternatives; note `kernel_size` pairs with `kernel_h`/`kernel_w`,
    reference: caffe.proto:499-512, 781-795)."""
    stem = name[:-5] if name.endswith("_size") else name
    h = msg.get(stem + "_h")
    w = msg.get(stem + "_w")
    if h is not None or w is not None:
        return (int(h) if h is not None else default,
                int(w) if w is not None else default)
    vals = msg.getlist(name)
    if not vals:
        return (default, default)
    if len(vals) == 1:
        return (int(vals[0]), int(vals[0]))
    return tuple(int(v) for v in vals)


class ConvolutionParameter(View):
    # caffe.proto:495-541: pad/kernel_size/stride are *repeated* (nd conv),
    # with _h/_w 2-D alternatives.
    DEFAULTS = dict(num_output=0, bias_term=True, group=1, axis=1,
                    force_nd_im2col=False)

    def _dims(self, name: str, default: int) -> tuple:
        return _resolve_hw(self.msg, name, default)

    @property
    def kernel(self) -> tuple:
        return self._dims("kernel_size", 0)

    @property
    def pad(self) -> tuple:
        return self._dims("pad", 0)

    @property
    def stride(self) -> tuple:
        return self._dims("stride", 1)

    @property
    def dilation(self) -> tuple:
        return self._dims("dilation", 1)

    @property
    def weight_filler(self) -> FillerParameter:
        return FillerParameter(self.msg.get("weight_filler"))

    @property
    def bias_filler(self) -> FillerParameter:
        return FillerParameter(self.msg.get("bias_filler"))


class PoolingParameter(View):
    # caffe.proto:777-801
    DEFAULTS = dict(pool="MAX", global_pooling=False)

    @property
    def kernel(self) -> tuple:
        return _resolve_hw(self.msg, "kernel_size", 0)

    @property
    def pads(self) -> tuple:
        return _resolve_hw(self.msg, "pad", 0)

    @property
    def strides(self) -> tuple:
        return _resolve_hw(self.msg, "stride", 1)


class InnerProductParameter(View):
    DEFAULTS = dict(num_output=0, bias_term=True, axis=1)

    @property
    def weight_filler(self) -> FillerParameter:
        return FillerParameter(self.msg.get("weight_filler"))

    @property
    def bias_filler(self) -> FillerParameter:
        return FillerParameter(self.msg.get("bias_filler"))


class LRNParameter(View):
    DEFAULTS = dict(local_size=5, alpha=1.0, beta=0.75,
                    norm_region="ACROSS_CHANNELS", k=1.0)


class ReLUParameter(View):
    DEFAULTS = dict(negative_slope=0.0)


class PReLUParameter(View):
    DEFAULTS = dict(channel_shared=False)

    @property
    def filler(self) -> FillerParameter:
        f = FillerParameter(self.msg.get("filler"))
        if not f.msg.has("type"):  # PReLU default init is 0.25 (prelu_layer.cpp)
            f.msg.set("type", "constant")
            f.msg.set("value", 0.25)
        return f


class DropoutParameter(View):
    DEFAULTS = dict(dropout_ratio=0.5)


class PowerParameter(View):
    DEFAULTS = dict(power=1.0, scale=1.0, shift=0.0)


class ExpParameter(View):
    DEFAULTS = dict(base=-1.0, scale=1.0, shift=0.0)


class LogParameter(View):
    DEFAULTS = dict(base=-1.0, scale=1.0, shift=0.0)


class ConcatParameter(View):
    DEFAULTS = dict(axis=1, concat_dim=1)


class SliceParameter(View):
    DEFAULTS = dict(axis=1, slice_dim=1)

    @property
    def slice_points(self) -> List[int]:
        return [int(v) for v in self.msg.getlist("slice_point")]


class EltwiseParameter(View):
    DEFAULTS = dict(operation="SUM", stable_prod_grad=True)

    @property
    def coeffs(self) -> List[float]:
        return [float(v) for v in self.msg.getlist("coeff")]


class SoftmaxParameter(View):
    DEFAULTS = dict(axis=1)


class AccuracyParameter(View):
    DEFAULTS = dict(top_k=1, axis=1)

    @property
    def ignore_label(self) -> Optional[int]:
        v = self.msg.get("ignore_label")
        return None if v is None else int(v)


class LossParameter(View):
    DEFAULTS = dict(normalize=True)

    @property
    def ignore_label(self) -> Optional[int]:
        v = self.msg.get("ignore_label")
        return None if v is None else int(v)


class HingeLossParameter(View):
    DEFAULTS = dict(norm="L1")


class ContrastiveLossParameter(View):
    DEFAULTS = dict(margin=1.0, legacy_version=False)


class InfogainLossParameter(View):
    DEFAULTS = dict(source="")


class FlattenParameter(View):
    DEFAULTS = dict(axis=1, end_axis=-1)


class ReshapeParameter(View):
    DEFAULTS = dict(axis=0, num_axes=-1)

    @property
    def shape_dims(self) -> List[int]:
        sh = self.msg.get("shape")
        if sh is None:
            return []
        return [int(d) for d in sh.getlist("dim")]


class TileParameter(View):
    DEFAULTS = dict(axis=1, tiles=1)


class EmbedParameter(View):
    DEFAULTS = dict(num_output=0, input_dim=0, bias_term=True)

    @property
    def weight_filler(self) -> FillerParameter:
        return FillerParameter(self.msg.get("weight_filler"))

    @property
    def bias_filler(self) -> FillerParameter:
        return FillerParameter(self.msg.get("bias_filler"))


class ReductionParameter(View):
    DEFAULTS = dict(operation="SUM", axis=0, coeff=1.0)


class ArgMaxParameter(View):
    DEFAULTS = dict(out_max_val=False, top_k=1)

    @property
    def axis(self) -> Optional[int]:
        v = self.msg.get("axis")
        return None if v is None else int(v)


class ThresholdParameter(View):
    DEFAULTS = dict(threshold=0.0)


class BatchNormParameter(View):
    DEFAULTS = dict(moving_average_fraction=0.999, eps=1e-5)

    @property
    def use_global_stats(self) -> Optional[bool]:
        v = self.msg.get("use_global_stats")
        return None if v is None else bool(v)


class MVNParameter(View):
    DEFAULTS = dict(normalize_variance=True, across_channels=False, eps=1e-9)


class SPPParameter(View):
    DEFAULTS = dict(pyramid_height=0, pool="MAX")


class BatchReindexParameter(View):
    DEFAULTS: dict[str, Any] = {}


class TransformationParameter(View):
    # caffe.proto:401-421
    DEFAULTS = dict(scale=1.0, mirror=False, crop_size=0, mean_file="",
                    force_color=False, force_gray=False)

    @property
    def mean_values(self) -> List[float]:
        return [float(v) for v in self.msg.getlist("mean_value")]


class DataParameter(View):
    DEFAULTS = dict(source="", batch_size=0, backend="LEVELDB", rand_skip=0,
                    scale=1.0, mirror=False, crop_size=0, mean_file="", prefetch=4)


class MemoryDataParameter(View):
    DEFAULTS = dict(batch_size=0, channels=0, height=0, width=0)


class ImageDataParameter(View):
    DEFAULTS = dict(source="", batch_size=1, rand_skip=0, shuffle=False,
                    new_height=0, new_width=0, is_color=True, scale=1.0,
                    mirror=False, crop_size=0, mean_file="", root_folder="")


class HDF5DataParameter(View):
    DEFAULTS = dict(source="", batch_size=0, shuffle=False)


class HDF5OutputParameter(View):
    DEFAULTS = dict(file_name="")


class WindowDataParameter(View):
    DEFAULTS = dict(source="", scale=1.0, mean_file="", batch_size=0,
                    crop_size=0, mirror=False, fg_threshold=0.5,
                    bg_threshold=0.5, fg_fraction=0.25, context_pad=0,
                    crop_mode="warp", cache_images=False, root_folder="")


class DummyDataParameter(View):
    @property
    def shapes(self) -> List[List[int]]:
        return [[int(d) for d in s.getlist("dim")] for s in self.msg.getlist("shape")]

    @property
    def data_fillers(self) -> List[FillerParameter]:
        return [FillerParameter(m) for m in self.msg.getlist("data_filler")]


class AttentionParameter(View):
    """Framework-extension layer param (this framework's own addition, the
    way JavaDataParameter was SparkNet's — caffe.proto:991 precedent):
    multi-head self-attention for sequence models.  method: "dense" or
    "blockwise" (ops/attention.py); blockwise is the memory-linear path
    long sequences need."""
    DEFAULTS = dict(num_heads=1, causal=False, method="dense",
                    block_size=128, bias_term=True)

    @property
    def weight_filler(self):
        return FillerParameter(self.msg.get("weight_filler"))

    @property
    def bias_filler(self):
        return FillerParameter(self.msg.get("bias_filler"))


class MoEParameter(View):
    """Framework-extension layer param (like AttentionParameter — the
    JavaDataParameter precedent, caffe.proto:991): mixture-of-experts FFN
    with top-k routing and static capacity (ops/moe.py); expert-parallel
    execution over a mesh axis lives in parallel/expert.py.  hidden_dim 0
    means 4x the input width.  aux_loss_weight adds the Switch
    load-balancing loss to the training objective."""
    DEFAULTS = dict(num_experts=1, hidden_dim=0, k=1, capacity_factor=1.25,
                    aux_loss_weight=0.01, bias_term=True)

    @property
    def weight_filler(self):
        return FillerParameter(self.msg.get("weight_filler"))

    @property
    def bias_filler(self):
        return FillerParameter(self.msg.get("bias_filler"))


class PythonParameter(View):
    # caffe.proto:810-817 — module/layer name a user PythonLayer class,
    # param_str is free-form config handed to the instance before setup()
    DEFAULTS = dict(module="", layer="", param_str="")


class JavaDataParameter(View):
    """SparkNet's own layer param (reference: caffe.proto:991-993)."""

    @property
    def shape_dims(self) -> List[int]:
        sh = self.msg.get("shape")
        if sh is None:
            return []
        return [int(d) for d in sh.getlist("dim")]


class ParamSpec(View):
    # caffe.proto:286-304
    DEFAULTS = dict(name="", lr_mult=1.0, decay_mult=1.0, share_mode="STRICT")


class BlobShape(View):
    @property
    def dims(self) -> List[int]:
        return [int(d) for d in self.msg.getlist("dim")]


class NetStateRule(View):
    # caffe.proto:262-284
    @property
    def phase(self) -> Optional[str]:
        v = self.msg.get("phase")
        return None if v is None else str(v)

    @property
    def min_level(self) -> Optional[int]:
        v = self.msg.get("min_level")
        return None if v is None else int(v)

    @property
    def max_level(self) -> Optional[int]:
        v = self.msg.get("max_level")
        return None if v is None else int(v)

    @property
    def stages(self) -> List[str]:
        return [str(s) for s in self.msg.getlist("stage")]

    @property
    def not_stages(self) -> List[str]:
        return [str(s) for s in self.msg.getlist("not_stage")]


class NetState(View):
    DEFAULTS = dict(phase="TEST", level=0)

    @property
    def stages(self) -> List[str]:
        return [str(s) for s in self.msg.getlist("stage")]


_PARAM_VIEWS = {
    "convolution_param": ConvolutionParameter,
    "pooling_param": PoolingParameter,
    "inner_product_param": InnerProductParameter,
    "lrn_param": LRNParameter,
    "relu_param": ReLUParameter,
    "prelu_param": PReLUParameter,
    "dropout_param": DropoutParameter,
    "power_param": PowerParameter,
    "exp_param": ExpParameter,
    "log_param": LogParameter,
    "concat_param": ConcatParameter,
    "slice_param": SliceParameter,
    "eltwise_param": EltwiseParameter,
    "softmax_param": SoftmaxParameter,
    "accuracy_param": AccuracyParameter,
    "loss_param": LossParameter,
    "hinge_loss_param": HingeLossParameter,
    "contrastive_loss_param": ContrastiveLossParameter,
    "infogain_loss_param": InfogainLossParameter,
    "flatten_param": FlattenParameter,
    "reshape_param": ReshapeParameter,
    "tile_param": TileParameter,
    "embed_param": EmbedParameter,
    "reduction_param": ReductionParameter,
    "argmax_param": ArgMaxParameter,
    "threshold_param": ThresholdParameter,
    "batch_norm_param": BatchNormParameter,
    "mvn_param": MVNParameter,
    "spp_param": SPPParameter,
    "transform_param": TransformationParameter,
    "data_param": DataParameter,
    "memory_data_param": MemoryDataParameter,
    "image_data_param": ImageDataParameter,
    "hdf5_data_param": HDF5DataParameter,
    "hdf5_output_param": HDF5OutputParameter,
    "window_data_param": WindowDataParameter,
    "dummy_data_param": DummyDataParameter,
    "java_data_param": JavaDataParameter,
    "python_param": PythonParameter,
    "attention_param": AttentionParameter,
    "moe_param": MoEParameter,
}


class LayerParameter(View):
    # caffe.proto:310-399
    DEFAULTS = dict(name="", type="")

    @property
    def bottoms(self) -> List[str]:
        return [str(b) for b in self.msg.getlist("bottom")]

    @property
    def tops(self) -> List[str]:
        return [str(t) for t in self.msg.getlist("top")]

    @property
    def params(self) -> List[ParamSpec]:
        return [ParamSpec(m) for m in self.msg.getlist("param")]

    @property
    def include_rules(self) -> List[NetStateRule]:
        return [NetStateRule(m) for m in self.msg.getlist("include")]

    @property
    def exclude_rules(self) -> List[NetStateRule]:
        return [NetStateRule(m) for m in self.msg.getlist("exclude")]

    @property
    def loss_weights(self) -> List[float]:
        return [float(v) for v in self.msg.getlist("loss_weight")]

    @property
    def phase(self) -> Optional[str]:
        v = self.msg.get("phase")
        return None if v is None else str(v)

    def param_view(self, which: str) -> Any:
        cls = _PARAM_VIEWS[which]
        return cls(self.msg.get(which))

    def __getattr__(self, name: str):
        if name in _PARAM_VIEWS:
            return _PARAM_VIEWS[name](self.msg.get(name))
        return super().__getattr__(name)


class NetParameter(View):
    # caffe.proto:64-100
    DEFAULTS = dict(name="", force_backward=False, debug_info=False)

    @property
    def layers(self) -> List[LayerParameter]:
        # modern field `layer`; legacy `layers` (V0/V1) trees are upgraded on
        # load by proto/upgrade.py.
        return [LayerParameter(m) for m in self.msg.getlist("layer")]

    @property
    def input_blobs(self) -> List[str]:
        return [str(s) for s in self.msg.getlist("input")]

    @property
    def input_shapes(self) -> List[List[int]]:
        shapes = [[int(d) for d in s.getlist("dim")]
                  for s in self.msg.getlist("input_shape")]
        if not shapes and self.msg.has("input_dim"):
            dims = [int(d) for d in self.msg.getlist("input_dim")]
            shapes = [dims[i:i + 4] for i in range(0, len(dims), 4)]
        return shapes

    @property
    def state(self) -> NetState:
        return NetState(self.msg.get("state"))

    def add_layer(self, layer_msg: Message, index: Optional[int] = None) -> None:
        if index is None:
            self.msg.add("layer", layer_msg)
        else:
            lst = self.msg._fields.setdefault("layer", [])
            lst.insert(index, layer_msg)


class SolverParameter(View):
    # caffe.proto:102-244
    DEFAULTS = dict(
        net="", train_net="", test_interval=0, test_compute_loss=False,
        test_initialization=True, base_lr=0.01, display=0, average_loss=1,
        max_iter=0, iter_size=1, lr_policy="fixed", gamma=0.1, power=1.0,
        momentum=0.0, weight_decay=0.0, regularization_type="L2", stepsize=0,
        clip_gradients=-1.0, snapshot=0, snapshot_prefix="",
        snapshot_diff=False, snapshot_format="BINARYPROTO", solver_mode="GPU",
        device_id=0, random_seed=-1, type="SGD", delta=1e-8, momentum2=0.999,
        rms_decay=0.99, debug_info=False, snapshot_after_train=True,
    )

    @property
    def net_param(self) -> Optional[NetParameter]:
        m = self.msg.get("net_param")
        return None if m is None else NetParameter(m)

    @property
    def train_net_param(self) -> Optional[NetParameter]:
        m = self.msg.get("train_net_param")
        return None if m is None else NetParameter(m)

    @property
    def test_iters(self) -> List[int]:
        return [int(v) for v in self.msg.getlist("test_iter")]

    @property
    def train_state(self) -> Optional["NetState"]:
        """NetState merged into the TRAIN net's filter state
        (caffe.proto:135; phase is forced to TRAIN by the solver)."""
        m = self.msg.get("train_state")
        return None if m is None else NetState(m)

    @property
    def test_states(self) -> List["NetState"]:
        """One NetState per test net (caffe.proto:136); this framework
        evaluates test net 0, matching the bridge
        (ccaffe.cpp:235-243 solver_test -> TestAndStoreResult(0, ...))."""
        return [NetState(m) for m in self.msg.getlist("test_state")]

    @property
    def stepvalues(self) -> List[int]:
        return [int(v) for v in self.msg.getlist("stepvalue")]

    @property
    def legacy_solver_type(self) -> Optional[str]:
        """Old enum field `solver_type` (caffe.proto:232-241); maps to `type`."""
        v = self.msg.get("solver_type")
        return None if v is None else str(v)

    def resolved_type(self) -> str:
        if self.msg.has("type"):
            return str(self.msg.get("type"))
        legacy = self.legacy_solver_type
        if legacy is not None:
            # enum names or numeric values (caffe.proto:232-241)
            table = {"SGD": "SGD", "NESTEROV": "Nesterov", "ADAGRAD": "AdaGrad",
                     "RMSPROP": "RMSProp", "ADADELTA": "AdaDelta", "ADAM": "Adam",
                     "0": "SGD", "1": "Nesterov", "2": "AdaGrad", "3": "RMSProp",
                     "4": "AdaDelta", "5": "Adam"}
            key = str(legacy)
            if key not in table:
                raise ValueError(f"unknown solver_type {legacy!r}")
            return table[key]
        return "SGD"


def load_net_prototxt(path: str) -> NetParameter:
    """Parse a net prototxt, transparently upgrading legacy V0/V1 formats
    (reference: ProtoLoader.scala:9-29 via C++;
    upgrade_proto.cpp ReadNetParamsFromTextFileOrDie)."""
    from . import upgrade
    return NetParameter(upgrade.upgrade_net_as_needed(parse_file(path)))


def parse_net_text(text: str) -> NetParameter:
    from . import upgrade
    return NetParameter(upgrade.upgrade_net_as_needed(parse(text)))


def load_solver_prototxt(path: str) -> SolverParameter:
    from . import upgrade
    return SolverParameter(upgrade.upgrade_solver_as_needed(parse_file(path)))


def load_solver_prototxt_with_net(solver_path: str, net: NetParameter,
                                  ) -> SolverParameter:
    """Inline a net into a solver param, clearing file-based net refs and
    engine-side snapshotting (reference: ProtoLoader.scala:31-43)."""
    sp = load_solver_prototxt(solver_path)
    for f in ("net", "train_net", "test_net"):
        sp.msg.clear(f)
    sp.msg.set("net_param", net.msg.copy())
    # SparkNet drives snapshots from the driver, not the engine
    sp.msg.clear("snapshot")
    sp.msg.set("snapshot_after_train", False)
    sp.msg.set("snapshot_prefix", "/tmp/sparknet_tpu")
    return sp


def _read_binaryproto_message(path: str, msg_name: str):
    """Shared binary read: file -> Message under the repo parser
    contract (file-naming ValueError), with skipped unknown fields
    surfaced on stderr — silent data loss is never acceptable in an
    upgrade tool."""
    from .binary_codec import decode_message

    try:
        buf = open(path, "rb").read()
    except OSError as e:
        raise ValueError(f"{path}: {e}") from None
    unknown: list = []
    try:
        msg = decode_message(buf, msg_name, unknown)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from None
    if unknown:
        import sys
        print(f"{path}: skipped {len(unknown)} unknown field(s) "
              f"{sorted(set(unknown))[:8]}", file=sys.stderr)
    return msg


def load_net_binaryproto(path: str) -> NetParameter:
    """Read a BINARY NetParameter (the .caffemodel wire format),
    transparently upgrading legacy V0/V1 formats — the read half of
    tools/upgrade_net_proto_binary.cpp (upgrade_proto.cpp
    ReadNetParamsFromBinaryFileOrDie)."""
    from . import upgrade

    msg = _read_binaryproto_message(path, "NetParameter")
    return NetParameter(upgrade.upgrade_net_as_needed(msg))


def save_net_binaryproto(path: str, net: NetParameter) -> None:
    """Write a NetParameter in the binary wire format (the write half of
    tools/upgrade_net_proto_binary.cpp WriteProtoToBinaryFile)."""
    from .binary_codec import encode_message

    data = encode_message(net.msg, "NetParameter")
    with open(path, "wb") as f:
        f.write(data)


def load_solver_binaryproto(path: str) -> SolverParameter:
    """Binary SolverParameter read + legacy solver_type upgrade (the
    binary sibling of load_solver_prototxt; reference solver protos are
    usually text, but the wire form round-trips identically)."""
    from . import upgrade

    msg = _read_binaryproto_message(path, "SolverParameter")
    return SolverParameter(upgrade.upgrade_solver_as_needed(msg))


def save_solver_binaryproto(path: str, sp: SolverParameter) -> None:
    from .binary_codec import encode_message

    data = encode_message(sp.msg, "SolverParameter")
    with open(path, "wb") as f:
        f.write(data)


def replace_data_layers(net: NetParameter, train_batch_size: int,
                        test_batch_size: int, channels: int, height: int,
                        width: int, tops=("data", "label")) -> NetParameter:
    """Swap the first two (data) layers for train+test in-memory feed layers
    with the given batch/shape (reference: ProtoLoader.scala:50-57,
    Layers.scala:18-40 `RDDLayer`).  `tops` overrides the fed blob names
    for nets whose data layer feeds differently-named tops (the bundled
    siamese workflow's pair_data/sim, mnist_siamese_train_test.prototxt)."""
    out = NetParameter(net.msg.copy())
    layers = out.msg.getlist("layer")
    # Drop every leading data-source layer (the reference drops exactly the
    # first two; we generalize to any number of leading data layers).
    data_types = {"Data", "ImageData", "MemoryData", "HDF5Data", "WindowData",
                  "DummyData", "JavaData"}
    n_data = 0
    while n_data < len(layers) and str(
            LayerParameter(layers[n_data]).type) in data_types:
        n_data += 1
    rest = layers[max(n_data, 1):]
    top_lines = "\n".join(f'top: "{t}"' for t in tops)

    def make(phase: str, batch: int) -> Message:
        m = parse(
            f'name: "data" type: "MemoryData"\n{top_lines}\n'
            f'include {{ phase: {phase} }}\n'
            f'memory_data_param {{ batch_size: {batch} channels: {channels} '
            f'height: {height} width: {width} }}\n'
        )
        return m

    out.msg._fields["layer"] = [make("TRAIN", train_batch_size),
                                make("TEST", test_batch_size)] + rest
    return out
