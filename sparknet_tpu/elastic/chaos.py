"""Deterministic fault injection for the elastic runtime.

A FaultPlan is a pure function of (seed, round, slot, attempt): the same
plan replayed against the same schedule produces the identical fault
sequence, so every elastic behavior — drops, stragglers, crashes, quorum
retries — is testable on the 8-virtual-device CPU mesh with bitwise
reproducibility (tests/test_elastic.py pins two full runs equal).  No
wall-clock or global RNG state enters any decision; "time" in a plan is
SIMULATED seconds derived from τ and a per-step cost model, which is what
lets the straggler A/B acceptance hold on a one-core box.

Spec grammar (``FaultPlan.from_spec``), comma-separated tokens:

    straggler:<slot>x<mult>   slot runs <mult>× slower every round
    crash:<slot>@<round>      slot crashes permanently at round
    drop:<prob>               every (round, slot) drops with prob
    delay:<prob>@<seconds>    transient extra delay with prob

e.g. ``straggler:1x20,crash:2@3,drop:0.05``.  Malformed specs die with a
ValueError naming the bad token (the repo-wide parser contract: never an
IndexError out of a parse).
"""

from __future__ import annotations

import dataclasses
import hashlib
import struct
from typing import Dict, Optional


def _u01(seed: int, *keys) -> float:
    """Uniform [0,1) as a pure hash of (seed, *keys) — query-order
    independent, unlike a stateful RNG stream, so a retry loop that asks
    about slots in any order sees the same draws."""
    h = hashlib.sha256(repr((int(seed),) + tuple(keys)).encode()).digest()
    return struct.unpack("<Q", h[:8])[0] / float(1 << 64)


# public alias: serving/resilience.py's ServeFaultPlan draws from the
# SAME keyed-hash stream discipline, so every chaos subsystem shares one
# determinism story (two same-seed plans agree bitwise on every draw)
u01 = _u01


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Seeded per-run fault schedule.

    stragglers: slot -> simulated per-step slowdown multiplier (>= 1).
    crashes: slot -> round at which the slot permanently crashes.
    drop_prob: per-(round, slot, attempt) chance a report is lost.
    delay_prob/delay_s: per-(round, slot, attempt) transient extra delay.
    """

    seed: int = 0
    stragglers: Dict[int, float] = dataclasses.field(default_factory=dict)
    crashes: Dict[int, int] = dataclasses.field(default_factory=dict)
    drop_prob: float = 0.0
    delay_prob: float = 0.0
    delay_s: float = 0.0

    def __post_init__(self):
        for slot, mult in self.stragglers.items():
            if mult < 1.0:
                raise ValueError(f"straggler multiplier for slot {slot} "
                                 f"must be >= 1, got {mult}")
        for p, what in ((self.drop_prob, "drop_prob"),
                        (self.delay_prob, "delay_prob")):
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{what} must be in [0, 1], got {p}")

    # ------------------------------------------------------------- queries
    def straggler_mult(self, slot: int) -> float:
        return float(self.stragglers.get(int(slot), 1.0))

    def crash_round(self, slot: int) -> Optional[int]:
        r = self.crashes.get(int(slot))
        return None if r is None else int(r)

    def crashed(self, round_idx: int, slot: int) -> bool:
        r = self.crash_round(slot)
        return r is not None and round_idx >= r

    def drops(self, round_idx: int, slot: int, attempt: int = 0) -> bool:
        if self.drop_prob <= 0.0:
            return False
        return _u01(self.seed, "drop", round_idx, slot,
                    attempt) < self.drop_prob

    def transient_delay_s(self, round_idx: int, slot: int,
                          attempt: int = 0) -> float:
        if self.delay_prob <= 0.0 or self.delay_s <= 0.0:
            return 0.0
        if _u01(self.seed, "delay", round_idx, slot,
                attempt) < self.delay_prob:
            return float(self.delay_s)
        return 0.0

    def report_s(self, round_idx: int, slot: int, base_s: float,
                 attempt: int = 0) -> float:
        """Simulated seconds until this slot's round report: base τ-step
        cost scaled by its straggler multiplier, plus any transient
        delay drawn for (round, slot, attempt)."""
        return (float(base_s) * self.straggler_mult(slot)
                + self.transient_delay_s(round_idx, slot, attempt))

    # -------------------------------------------------------------- parser
    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultPlan":
        """Parse the comma-separated token grammar (module docstring)."""
        stragglers: Dict[int, float] = {}
        crashes: Dict[int, int] = {}
        drop_prob = delay_prob = delay_s = 0.0
        for raw in (t.strip() for t in (spec or "").split(",")):
            if not raw:
                continue
            kind, sep, rest = raw.partition(":")
            try:
                if kind == "straggler" and sep:
                    slot, _, mult = rest.partition("x")
                    stragglers[int(slot)] = float(mult)
                elif kind == "crash" and sep:
                    slot, _, rnd = rest.partition("@")
                    crashes[int(slot)] = int(rnd)
                elif kind == "drop" and sep:
                    drop_prob = float(rest)
                elif kind == "delay" and sep:
                    prob, _, secs = rest.partition("@")
                    delay_prob, delay_s = float(prob), float(secs)
                else:
                    raise ValueError("unknown token kind")
            except ValueError as e:
                raise ValueError(
                    f"malformed chaos spec token {raw!r} in {spec!r}: {e} "
                    f"(grammar: straggler:<slot>x<mult>, crash:<slot>@<r>, "
                    f"drop:<p>, delay:<p>@<s>)") from None
        return cls(seed=int(seed), stragglers=stragglers, crashes=crashes,
                   drop_prob=drop_prob, delay_prob=delay_prob,
                   delay_s=delay_s)
