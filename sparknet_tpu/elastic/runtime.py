"""Elastic training runtime: partial-quorum rounds, membership changes
with snapshot catch-up, and adaptive τ — layered over DistributedSolver.

The reference's driver loop is rigidly synchronous: `collect` waits for
every executor, so one straggler stalls the fleet and one lost executor
kills the job (reference: CifarApp.scala:95-136 collect over all
workers).  This runtime keeps the solver's ONE-fused-program-per-round
design and adds the backup-worker/partial-quorum recipe on top
(PAPERS.md: "TensorFlow: A system for large-scale machine learning",
§4.4): every round still computes all worker shards, but only the slots
that "reported" inside the deadline enter the τ-interval average —
a masked psum (dist.py masked round variant) — and dropped slots adopt
the quorum average, which is precisely the periodic-averaging form of
straggler re-sync.

Membership is SLOT-based: the mesh's worker axis is fixed at
construction, and elasticity is which slots are ACTIVE.  A leave/crash
deactivates a slot (its shard assignment is deterministically rebalanced
onto the survivors, data/partition.py); a join reactivates a slot,
catching it up from the newest stepped snapshot (utils/orbax_ckpt.py
resolve_latest) or, with no snapshot yet, from a live peer replica, then
entering at the next round barrier.

Everything the controller decides — deadlines, drops, stragglers, stall
seconds, τ moves — runs on SIMULATED time derived from a FaultPlan and a
per-step cost model, never wall-clock, so chaos runs replay bitwise on
the 8-virtual-device CPU mesh (tests/test_elastic.py pins two runs
producing identical event logs AND identical final params).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..data.partition import initial_assignment, rebalance, shards_of
from ..obs.metrics import MetricsRegistry
from ..utils.orbax_ckpt import resolve_latest, restore_auto, save_step
from .chaos import FaultPlan
from .tau import AdaptiveTau


class QuorumError(RuntimeError):
    """A round could not assemble min_quorum reports within max_retries."""


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "").strip()
    return int(v) if v else default


def _env_float(name: str, default: Optional[float]) -> Optional[float]:
    v = os.environ.get(name, "").strip()
    return float(v) if v else default


class ShardedFeed:
    """A per-worker data source that draws round-robin from an assigned
    set of dataset shards, each backed by its own lazily-created stream.

    `set_shards` re-targets the feed when the elastic runtime rebalances
    (data/partition.rebalance); streams persist across reassignment so a
    shard returning to a worker resumes from its cursor (warm), and the
    pull sequence is a pure function of the assignment history —
    deterministic under chaos replay.  `stream_safe` marks the feed
    round-agnostic for DistributedSolver's prefetch guard; the elastic
    runtime itself refuses prefetch (τ can change between rounds)."""

    stream_safe = True

    def __init__(self, make_stream: Callable[[int], Callable[[], dict]],
                 shard_ids: Sequence[int]) -> None:
        self._make = make_stream
        self._streams: Dict[int, Callable[[], dict]] = {}
        self._ids: List[int] = []
        self._i = 0
        self.set_shards(shard_ids)

    @property
    def shard_ids(self) -> List[int]:
        return list(self._ids)

    def set_shards(self, ids: Sequence[int]) -> None:
        self._ids = sorted(int(s) for s in ids)
        if not self._ids:
            raise ValueError("ShardedFeed needs at least one shard")
        for s in self._ids:
            if s not in self._streams:
                self._streams[s] = self._make(s)

    def __call__(self) -> dict:
        s = self._ids[self._i % len(self._ids)]
        self._i += 1
        return self._streams[s]()


class ElasticRuntime:
    """Membership/round controller over a DistributedSolver.

    deadline_s=None is the FULL BARRIER: every active slot is waited for
    (and its simulated report time charged to stall), the reference
    semantics.  A finite deadline turns rounds into partial-quorum:
    slots whose simulated report exceeds it are masked out, subject to
    `min_quorum` — a round below quorum retries with exponential backoff
    (`sleep_fn` injectable so tests pass a recording stub) and dies with
    QuorumError after `max_retries`.

    step_time_s and comm_gbps are the simulation cost model: a round's
    base report time is τ·step_time_s scaled per-slot by the fault
    plan's straggler multipliers, and the communication cost fed to the
    adaptive-τ controller is param_bytes_moved / comm_gbps — both
    deterministic, which makes the A/B acceptance (strictly fewer
    stall-seconds under partial quorum) a telemetry fact, not a timing
    race."""

    def __init__(self, solver, *,
                 min_quorum: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 chaos: Optional[FaultPlan] = None,
                 adaptive: Optional[AdaptiveTau] = None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every: Optional[int] = None,
                 step_time_s: float = 0.05,
                 comm_gbps: float = 1.0,
                 max_retries: int = 3,
                 backoff_s: float = 0.05,
                 sleep_fn: Optional[Callable[[float], None]] = None,
                 metrics: Optional[MetricsRegistry] = None) -> None:
        if solver.mode != "average":
            raise ValueError("ElasticRuntime requires mode='average' — "
                             "partial quorum masks the τ-interval average")
        if solver.has_dcn:
            raise ValueError("ElasticRuntime runs on a flat worker mesh; "
                             "the (dcn, workers) hierarchy is unsupported")
        if solver._prefetch:
            raise ValueError(
                "ElasticRuntime is incompatible with prefetch: adaptive τ "
                "changes the staged batch shape between rounds — call "
                "set_prefetch(False) first")
        self.solver = solver
        n = solver.n_workers
        self.min_quorum = (min_quorum if min_quorum is not None
                           else _env_int("SPARKNET_ELASTIC_MIN_QUORUM",
                                         max(1, n // 2)))
        if not 1 <= self.min_quorum <= n:
            raise ValueError(f"min_quorum must be in [1, {n}], got "
                             f"{self.min_quorum}")
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("SPARKNET_ELASTIC_DEADLINE_S",
                                           None))
        self.chaos = chaos
        self.adaptive = adaptive
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = (snapshot_every if snapshot_every is not None
                               else _env_int(
                                   "SPARKNET_ELASTIC_SNAPSHOT_EVERY", 0))
        self.step_time_s = float(step_time_s)
        self.comm_gbps = float(comm_gbps)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.sleep_fn = sleep_fn if sleep_fn is not None else time.sleep
        self.active = set(range(n))
        self.events: List[Dict[str, Any]] = []
        self.stall_sim_s = 0.0
        self._scheduled_joins: Dict[int, int] = {}
        # a planned crash fires ONCE: a slot that later rejoins (fresh
        # worker occupying the freed slot) must not be re-crashed by the
        # same plan entry
        self._crashes_applied: set = set()
        self._assignment: Optional[Dict[int, int]] = None
        srcs = solver.train_sources or []
        if srcs and all(hasattr(s, "set_shards") for s in srcs):
            # runtime-managed sharding: seed the assignment from what the
            # feeds currently own so rebalances preserve warm shards
            self._assignment = {}
            for w, s in enumerate(srcs):
                for sid in s.shard_ids:
                    self._assignment[sid] = w
        m = metrics if metrics is not None else MetricsRegistry()
        self.metrics = m
        self._g_quorum = m.gauge("elastic_quorum")
        self._g_active = m.gauge("elastic_active_workers")
        self._g_tau = m.gauge("elastic_tau")
        self._h_stall = m.histogram("elastic_stall_sim_seconds", window=4096)
        self._c_rounds = m.counter("elastic_rounds")
        self._c_retries = m.counter("elastic_quorum_retries")
        self._c_drops = m.counter("elastic_dropped_reports")
        self._c_leaves = m.counter("elastic_leaves")
        self._c_joins = m.counter("elastic_joins")
        self._c_snaps = m.counter("elastic_snapshots")
        self._g_active.set(len(self.active))
        self._g_tau.set(solver.tau)

    # ------------------------------------------------------------- events
    def _event(self, kind: str, **fields) -> Dict[str, Any]:
        rec = self.solver.append_round_event(kind, **fields)
        self.events.append(rec)
        return rec

    # --------------------------------------------------------- membership
    def leave(self, slot: int, reason: str = "leave") -> None:
        """Deactivate a slot: its reports stop entering rounds and its
        shards rebalance onto the survivors.  The slot's replica keeps
        computing inside the fused program (simulation-inherent); its
        results are masked out of every average."""
        slot = int(slot)
        if slot not in self.active:
            raise ValueError(f"slot {slot} is not active")
        if len(self.active) == 1:
            raise QuorumError("cannot deactivate the last active worker")
        self.active.discard(slot)
        moved: List[int] = []
        if self._assignment is not None:
            new = rebalance(self._assignment, sorted(self.active))
            moved = sorted(s for s in new if new[s] != self._assignment[s])
            self._assignment = new
            self._apply_assignment()
        self._c_leaves.inc()
        self._g_active.set(len(self.active))
        self._event(reason, slot=slot, active=sorted(self.active),
                    moved_shards=moved)

    def schedule_join(self, slot: int, round_idx: int) -> None:
        """Arm a join to happen at the round_idx round barrier (run())."""
        self._scheduled_joins[int(slot)] = int(round_idx)

    def join(self, slot: int) -> None:
        """Reactivate a slot at the current round barrier, catching its
        replica up from the newest stepped snapshot under snapshot_dir
        (orbax or native — resolve_latest finds either), or from a live
        peer replica when no snapshot exists yet."""
        slot = int(slot)
        if slot in self.active:
            raise ValueError(f"slot {slot} is already active")
        path = (resolve_latest(self.snapshot_dir)
                if self.snapshot_dir else None)
        if path is not None:
            _it, params, state = restore_auto(path)
            source = os.path.basename(path)
        else:
            peer = min(self.active)
            params = {k: np.asarray(v[peer])
                      for k, v in self.solver.params_w.items()}
            state = {k: tuple(np.asarray(h[peer]) for h in hs)
                     for k, hs in self.solver.state_w.items()}
            source = f"peer:{peer}"
        self._install_slot(slot, params, state)
        self.active.add(slot)
        moved: List[int] = []
        if self._assignment is not None:
            new = rebalance(self._assignment, sorted(self.active))
            moved = sorted(s for s in new if new[s] != self._assignment[s])
            self._assignment = new
            self._apply_assignment()
        self._c_joins.inc()
        self._g_active.set(len(self.active))
        self._event("join", slot=slot, source=source,
                    active=sorted(self.active), moved_shards=moved)

    def _apply_assignment(self) -> None:
        for w in sorted(self.active):
            src = self.solver.train_sources[w]
            src.set_shards(shards_of(self._assignment, w))

    def _install_slot(self, slot: int, params: Dict[str, Any],
                      state: Dict[str, tuple]) -> None:
        """Overwrite one worker row of params_w/state_w host-side and
        re-shard — the catch-up transfer a real joiner would receive."""
        solver = self.solver
        pw = {}
        for k, v in solver.params_w.items():
            a = np.asarray(v).copy()
            a[slot] = np.asarray(params[k], dtype=a.dtype)
            pw[k] = jnp.asarray(a)
        solver.params_w = jax.device_put(pw, solver._wsh)
        sw = {}
        for k, hs in solver.state_w.items():
            rows = []
            for i, h in enumerate(hs):
                a = np.asarray(h).copy()
                a[slot] = np.asarray(state[k][i], dtype=a.dtype)
                rows.append(jnp.asarray(a))
            sw[k] = tuple(rows)
        solver.state_w = jax.device_put(sw, solver._wsh)

    # ---------------------------------------------------------- snapshots
    def snapshot(self) -> Optional[str]:
        """Stepped snapshot of the lowest ACTIVE replica (post-average all
        included replicas are equal; slot 0 may be crashed, so "worker 0"
        is not the safe choice here the way it is in solver.snapshot)."""
        if not self.snapshot_dir:
            return None
        slot = min(self.active)
        solver = self.solver
        params = {k: np.asarray(v[slot])
                  for k, v in solver.params_w.items()}
        state = {k: tuple(np.asarray(h[slot]) for h in hs)
                 for k, hs in solver.state_w.items()}
        path = save_step(self.snapshot_dir, solver.round, solver.iter,
                         params, state)
        self._c_snaps.inc()
        self._event("snapshot", step=solver.round, slot=slot,
                    path=os.path.basename(path))
        return path

    # -------------------------------------------------------------- rounds
    def run_round(self) -> float:
        """One elastic round: apply scheduled crashes, assemble a quorum
        under the (simulated) deadline with retry/backoff, dispatch the
        masked round, account simulated stall, drive the adaptive-τ
        controller, and cut the snapshot cadence."""
        solver = self.solver
        r = solver.round
        if self.chaos is not None:
            for slot in sorted(self.active):
                if (self.chaos.crashed(r, slot)
                        and slot not in self._crashes_applied):
                    self._crashes_applied.add(slot)
                    self.leave(slot, reason="crash")
        base_s = solver.tau * self.step_time_s
        attempt = 0
        while True:
            report: Dict[int, float] = {}
            dropped: List[int] = []
            for slot in sorted(self.active):
                if self.chaos is not None:
                    if self.chaos.drops(r, slot, attempt):
                        dropped.append(slot)
                        continue
                    report[slot] = self.chaos.report_s(r, slot, base_s,
                                                       attempt)
                else:
                    report[slot] = base_s
            if dropped:
                self._c_drops.inc(len(dropped))
            if self.deadline_s is not None:
                included = {s: t for s, t in report.items()
                            if t <= self.deadline_s}
            else:
                included = report  # full barrier: wait for every report
            if len(included) >= self.min_quorum:
                break
            attempt += 1
            self._c_retries.inc()
            self._event("quorum_retry", attempt=attempt,
                        reported=sorted(included),
                        dropped=dropped, need=self.min_quorum)
            if attempt > self.max_retries:
                raise QuorumError(
                    f"round {r}: only {len(included)} of "
                    f"{len(self.active)} active workers reported "
                    f"(min_quorum={self.min_quorum}) after "
                    f"{self.max_retries} retries")
            self.sleep_fn(self.backoff_s * (2 ** (attempt - 1)))
        # simulated straggler stall: how long the round barrier waited
        # past the FASTEST included report — zero when included reports
        # are balanced, (mult-1)·τ·step under a straggler that made the
        # cut.  Dropped-by-deadline slots charge nothing: that is the
        # entire point of partial quorum, and what the A/B pins.
        stall = (max(included.values()) - min(included.values())
                 if included else 0.0)
        mask = np.zeros(solver.n_workers, dtype=np.float32)
        mask[sorted(included)] = 1.0
        loss = solver.run_round(mask=mask)
        self.stall_sim_s += stall
        self._h_stall.observe(stall)
        self._c_rounds.inc()
        self._g_quorum.set(len(included))
        self._g_tau.set(solver.tau)
        self._event("elastic_round", round_idx=r, quorum=len(included),
                    included=sorted(included),
                    missing=sorted(set(range(solver.n_workers))
                                   - set(included)),
                    stall_sim_s=round(stall, 6),
                    tau_effective=solver.tau, attempts=attempt)
        if self.adaptive is not None:
            comm_s = (2 * (solver.n_workers - 1) * solver._param_bytes
                      / (self.comm_gbps * 1e9))
            new_tau = self.adaptive.update(stall, comm_s)
            if new_tau != solver.tau:
                old = solver.tau
                solver.set_tau(new_tau)
                for src in solver.train_sources or []:
                    if hasattr(src, "tau"):
                        src.tau = new_tau
                self._g_tau.set(new_tau)
                self._event("tau_change", tau_from=old, tau_to=new_tau,
                            stall_s=round(stall, 6),
                            comm_s=round(comm_s, 6))
        if (self.snapshot_dir and self.snapshot_every
                and solver.round % self.snapshot_every == 0):
            self.snapshot()
        return loss

    def run(self, n_rounds: int) -> List[float]:
        """Drive n_rounds, admitting scheduled joins at round barriers."""
        losses = []
        for _ in range(int(n_rounds)):
            r = self.solver.round
            for slot, jr in sorted(self._scheduled_joins.items()):
                if jr <= r and slot not in self.active:
                    self.join(slot)
            losses.append(self.run_round())
        return losses

    def stats(self) -> Dict[str, Any]:
        return {"rounds": int(self._c_rounds.value),
                "active_workers": sorted(self.active),
                "stall_sim_s": round(self.stall_sim_s, 6),
                "tau": self.solver.tau,
                "quorum_retries": int(self._c_retries.value),
                "dropped_reports": int(self._c_drops.value),
                "leaves": int(self._c_leaves.value),
                "joins": int(self._c_joins.value),
                "snapshots": int(self._c_snaps.value),
                "events": len(self.events)}
