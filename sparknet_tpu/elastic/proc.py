"""Process-level elastic supervisor: REAL preemption over OS workers.

PR 10's ElasticRuntime proved the round algebra (partial-quorum masked
averaging, join/leave, seeded chaos) on simulated time inside one
process.  This module graduates it to real multi-process preemption —
the SparkNet failure model (arXiv:1511.06051 §3: workers may lag or die
between τ-step averaging rounds) and the TensorFlow stance that worker
failure + checkpoint recovery is a first-class system property
(arXiv:1605.08695 §4.2) — with nothing simulated:

- N worker subprocesses (elastic/proc_worker.py), each a single-chip
  Solver on its own data shard, driven by JSON round commands over
  stdin and reporting params through atomically-published npz files;
- crash detection by `Popen.poll()` — a `kill -9` mid-round excludes
  the worker from the round via the same partial-quorum average,
  host-side (`masked_host_average`, sequential float32 over sorted
  slots, mirroring the masked psum's survivor average);
- a wall-clock report deadline + file-mtime heartbeat watchdog (the
  real-time analogue of `parallel.dist.make_stage_deadline_hook` over
  `solver._stage_worker_s`), retry-with-backoff before a QuorumError;
- join = a FRESH process that catches up from the latest VALID snapshot
  (utils/orbax_ckpt.resolve_latest — manifest-checked, torn snapshots
  skipped);
- the seeded FaultPlan (elastic/chaos.py) drives REAL signals: a
  planned crash is a SIGKILL, a planned straggler is SIGSTOPped for the
  round (its heartbeat genuinely stalls) and SIGCONT'd after collect,
  so a chaos run is bitwise-replayable while every fault is an actual
  OS event (pinned by tests/test_elastic_proc.py);
- SIGINT means snapshot-then-drain (utils/signals.SNAPSHOT_STOP): cut a
  manifest-committed snapshot, stop the workers, exit cleanly.

Obs counters: worker_restarts, heartbeat_miss, proc_crashes,
quorum_retries, dropped_reports, snapshots; torn_snapshots_skipped is
process-wide in utils/orbax_ckpt and folded into stats().

Knobs: SPARKNET_ELASTIC_PROC (CLI default worker count),
SPARKNET_ELASTIC_PROC_DEADLINE_S (per-round report deadline, default
30), SPARKNET_ELASTIC_PROC_HEARTBEAT_S (worker heartbeat period,
default 0.25), SPARKNET_ELASTIC_MIN_QUORUM (shared with the in-process
runtime).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import signal
import subprocess
import tempfile
import time  # sleep only; timestamps flow through obs.trace.now_s
from typing import Any, Dict, List, Optional, Set

import numpy as np

from ..obs.metrics import MetricsRegistry
from ..obs.trace import now_s
from ..utils import orbax_ckpt
from ..utils.signals import SignalHandler, SolverAction
from . import ipc
from .chaos import FaultPlan
from .runtime import QuorumError


def masked_host_average(params_by_slot: Dict[int, Dict[str, np.ndarray]]
                        ) -> Dict[str, np.ndarray]:
    """Quorum average over the surviving slots, host-side: sequential
    left-to-right float32 accumulation in sorted-slot order — the same
    fixed reduction order every replay sees, mirroring the masked psum's
    `sum(p·w)/sum(w)` over survivors (parallel/dist.py)."""
    if not params_by_slot:
        raise ValueError("masked_host_average needs at least one report")
    slots = sorted(params_by_slot)
    out: Dict[str, np.ndarray] = {}
    for k in params_by_slot[slots[0]]:
        acc = np.array(params_by_slot[slots[0]][k], dtype=np.float32,
                       copy=True)
        for s in slots[1:]:
            acc = acc + np.asarray(params_by_slot[s][k], dtype=np.float32)
        out[k] = acc / np.float32(len(slots))
    return out


@dataclasses.dataclass
class _Worker:
    slot: int
    proc: subprocess.Popen
    cfg_path: str
    hb_path: str
    stderr_path: str
    stderr_f: Any


class ProcSupervisor:
    """Spawns and drives N elastic worker processes; one instance = one
    training run.  Use as a context manager (close() reaps every child,
    including SIGSTOP'd stragglers)."""

    def __init__(self, n_workers: int, *, tau: int = 2, seed: int = 7,
                 builder: str = "toy",
                 workdir: Optional[str] = None,
                 min_quorum: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None,
                 chaos: Optional[FaultPlan] = None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every: int = 0,
                 max_retries: int = 3, backoff_s: float = 0.25,
                 restore: bool = False,
                 round_log: Optional[str] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 worker_extra: Optional[Dict[str, Any]] = None,
                 spawn_timeout_s: float = 120.0,
                 action_source: Optional[SignalHandler] = None,
                 round_sleep_s: float = 0.0,
                 poll_s: float = 0.02) -> None:
        n_workers = int(n_workers)
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.n_workers = n_workers
        self.tau = int(tau)
        self.seed = int(seed)
        self.builder = str(builder)
        if min_quorum is None:
            min_quorum = int(os.environ.get(
                "SPARKNET_ELASTIC_MIN_QUORUM", "0") or 0) \
                or max(1, n_workers // 2)
        if not 1 <= int(min_quorum) <= n_workers:
            raise ValueError(f"min_quorum must be in [1, {n_workers}], "
                             f"got {min_quorum}")
        self.min_quorum = int(min_quorum)
        if deadline_s is None:
            deadline_s = float(os.environ.get(
                "SPARKNET_ELASTIC_PROC_DEADLINE_S", "30") or 30)
        self.deadline_s = float(deadline_s)
        if heartbeat_s is None:
            heartbeat_s = float(os.environ.get(
                "SPARKNET_ELASTIC_PROC_HEARTBEAT_S", "0.25") or 0.25)
        self.heartbeat_s = float(heartbeat_s)
        self.hb_miss_after_s = max(4.0 * self.heartbeat_s, 1.0)
        self._watchdog = ipc.MtimeWatchdog(self.hb_miss_after_s)
        self.chaos = chaos
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = int(snapshot_every)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.restore = bool(restore)
        self.round_log = round_log
        self.worker_extra = dict(worker_extra or {})
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.action_source = action_source
        self.round_sleep_s = float(round_sleep_s)
        self.poll_s = float(poll_s)

        self._own_workdir = workdir is None
        self.workdir = workdir
        self.workers: Dict[int, _Worker] = {}
        self.active: Set[int] = set()
        self.left: Dict[int, str] = {}
        self._joins: Dict[int, List[int]] = {}
        self.params_avg: Optional[Dict[str, np.ndarray]] = None
        self.iter_done = 0
        self.rounds_done = 0
        self.losses: List[float] = []
        self.events: List[Dict[str, Any]] = []
        self._crashes_applied: Set[int] = set()
        self._restored_from: Optional[str] = None
        self._started = False
        self._closed = False

        self.metrics = metrics or MetricsRegistry()
        self.c_restarts = self.metrics.counter("worker_restarts")
        self.c_hb_miss = self.metrics.counter("heartbeat_miss")
        self.c_crashes = self.metrics.counter("proc_crashes")
        self.c_rounds = self.metrics.counter("proc_rounds")
        self.c_retries = self.metrics.counter("quorum_retries")
        self.c_dropped = self.metrics.counter("dropped_reports")
        self.c_snapshots = self.metrics.counter("snapshots")
        self.g_active = self.metrics.gauge("proc_active_workers")
        self.g_quorum = self.metrics.gauge("proc_quorum")

    # ------------------------------------------------------------ lifecycle
    def __enter__(self) -> "ProcSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def start(self) -> "ProcSupervisor":
        if self._started:
            raise RuntimeError("supervisor already started")
        self._started = True
        if self.workdir is None:
            self.workdir = tempfile.mkdtemp(prefix="sparknet_proc_")
        os.makedirs(self.workdir, exist_ok=True)
        if self.snapshot_dir:
            os.makedirs(self.snapshot_dir, exist_ok=True)
        if self.restore and self.snapshot_dir:
            src = orbax_ckpt.resolve_latest(self.snapshot_dir)
            if src is not None:
                it, params, _state = orbax_ckpt.restore_auto(src)
                self.params_avg = {k: np.asarray(v)
                                   for k, v in params.items()}
                self.iter_done = int(it)
                self._restored_from = src
                self._event(kind="restore", source=src, iter=int(it))
        for slot in range(self.n_workers):
            self._spawn(slot)
        for slot in range(self.n_workers):
            self._wait_ready(self.workers[slot])
            self.active.add(slot)
        self.g_active.set(len(self.active))
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._drain()
        for w in self.workers.values():
            for stream in (w.proc.stdin, w.proc.stdout):
                try:
                    if stream:
                        stream.close()
                except OSError:
                    pass
            try:
                w.stderr_f.close()
            except OSError:
                pass
        if self._own_workdir and self.workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)

    def _drain(self) -> None:
        """Stop every live worker: SIGCONT (a SIGSTOP'd straggler cannot
        process a stop command), polite stop, then ipc.reap's
        terminate/kill ladder — the guaranteed kill path for every
        worker this module spawns."""
        for w in self.workers.values():
            if w.proc.poll() is not None:
                continue
            ipc.sigcont(w.proc.pid)
            try:
                w.proc.stdin.write(json.dumps({"cmd": "stop"}) + "\n")
                w.proc.stdin.flush()
            except (BrokenPipeError, ValueError, OSError):
                pass
        for w in self.workers.values():
            ipc.reap(w.proc)

    # ------------------------------------------------------------- spawning
    def _worker_cfg(self, slot: int, restore_root: Optional[str]) -> dict:
        cfg = {"slot": slot, "seed": self.seed, "tau": self.tau,
               "builder": self.builder,
               "heartbeat_path": os.path.join(self.workdir, f"hb_w{slot}"),
               "heartbeat_s": self.heartbeat_s,
               "restore_root": restore_root,
               "round_sleep_s": self.round_sleep_s}
        cfg.update(self.worker_extra)
        return cfg

    def _spawn(self, slot: int, restore_root: Optional[str] = None
               ) -> _Worker:
        cfg = self._worker_cfg(slot, restore_root)
        cfg_path = os.path.join(self.workdir, f"worker_{slot}.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        stderr_path = os.path.join(self.workdir, f"worker_{slot}.stderr")
        stderr_f = open(stderr_path, "ab")
        # ipc.spawn_worker: CPU-pinned env + start_new_session, so a
        # ctrl-C reaches ONLY the supervisor, which then does
        # snapshot-then-drain instead of every child dying mid-round
        proc = ipc.spawn_worker("sparknet_tpu.elastic.proc_worker",
                                cfg_path, stderr_f=stderr_f)
        w = _Worker(slot=slot, proc=proc, cfg_path=cfg_path,
                    hb_path=cfg["heartbeat_path"],
                    stderr_path=stderr_path, stderr_f=stderr_f)
        self.workers[slot] = w
        self._event(kind="spawn", slot=slot, pid=proc.pid,
                    restore_root=restore_root)
        return w

    def _wait_ready(self, w: _Worker) -> dict:
        return ipc.wait_ready_line(w.proc,
                                   timeout_s=self.spawn_timeout_s,
                                   what=f"worker {w.slot}",
                                   stderr_path=w.stderr_path)

    # ------------------------------------------------------------ telemetry
    def _event(self, **fields) -> None:
        self.events.append(fields)
        if self.round_log:
            with open(self.round_log, "a") as f:
                f.write(json.dumps(fields) + "\n")
                f.flush()

    def _hb_tick(self, slots, dt: float, hb_missed: Set[int]) -> None:
        for slot in slots:
            w = self.workers.get(slot)
            if w is None or not w.hb_path:
                continue
            if self._watchdog.tick(slot, w.hb_path, dt):
                self.c_hb_miss.inc()
                hb_missed.add(slot)

    # ------------------------------------------------------------ membership
    def schedule_join(self, slot: int, round_idx: int) -> None:
        slot, round_idx = int(slot), int(round_idx)
        if round_idx < self.rounds_done:
            raise ValueError(f"cannot schedule a join at past round "
                             f"{round_idx} (now at {self.rounds_done})")
        self._joins.setdefault(round_idx, []).append(slot)

    def _join(self, slot: int, round_idx: int) -> None:
        if slot in self.active:
            raise ValueError(f"slot {slot} is already active")
        old = self.workers.get(slot)
        if old is not None and old.proc.poll() is None:
            old.proc.kill()
            old.proc.wait(timeout=5)
        restore_root = self.snapshot_dir if self.snapshot_dir else None
        w = self._spawn(slot, restore_root=restore_root)
        ready = self._wait_ready(w)
        self.active.add(slot)
        self.left.pop(slot, None)
        self.c_restarts.inc()
        self.g_active.set(len(self.active))
        self._event(kind="join", slot=slot, round=round_idx,
                    source=ready.get("restored_from"),
                    iter=ready.get("iter"))

    def _mark_left(self, slot: int, reason: str, round_idx: int) -> None:
        self.active.discard(slot)
        self.left[slot] = reason
        self.g_active.set(len(self.active))
        self._event(kind="leave", slot=slot, round=round_idx,
                    reason=reason)

    def _kill_slot(self, slot: int, reason: str, round_idx: int) -> None:
        w = self.workers[slot]
        if w.proc.poll() is None:
            ipc.sigcont(w.proc.pid)
            w.proc.kill()
            try:
                w.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        self.c_crashes.inc()
        self._mark_left(slot, reason, round_idx)

    def kill_worker(self, slot: int, sig: int = signal.SIGKILL) -> None:
        """Deliver a REAL signal to a worker (tests/chaos tooling).  The
        supervisor does not mark anything — detection must happen through
        the same poll/deadline machinery a genuine fault exercises."""
        os.kill(self.workers[slot].proc.pid, sig)

    # ---------------------------------------------------------------- rounds
    def _write_bcast(self, round_idx: int) -> str:
        arrays = {f"param:{k}": np.asarray(v)
                  for k, v in self.params_avg.items()}
        arrays["__iter__"] = np.int64(self.iter_done)
        path = os.path.join(self.workdir, f"bcast_{round_idx:06d}.npz")
        ipc.atomic_write_npz(path, arrays)
        return path

    @staticmethod
    def _read_report(path: str) -> dict:
        with np.load(path) as data:
            return {"params": {k[len("param:"):]: np.array(data[k])
                               for k in data.files
                               if k.startswith("param:")},
                    "loss": float(data["__loss__"]),
                    "iter": int(data["__iter__"]),
                    "round": int(data["__round__"])}

    def run_round(self) -> float:
        """One τ-round over the worker fleet; returns the quorum-mean
        loss.  Raises QuorumError when fewer than min_quorum workers
        report within deadline_s across max_retries backoff windows."""
        if not self._started:
            raise RuntimeError("start() the supervisor first")
        r = self.rounds_done
        t_round0 = now_s()
        for slot in sorted(self._joins.pop(r, [])):
            self._join(slot, r)
        crashed_this_round: List[int] = []
        if self.chaos is not None:
            for slot in sorted(self.active):
                # one planned crash per slot: a fresh process joining the
                # freed slot must not be re-crashed by the same plan entry
                # (runtime.py `_crashes_applied` semantics)
                if (self.chaos.crashed(r, slot)
                        and slot not in self._crashes_applied):
                    self._crashes_applied.add(slot)
                    self._kill_slot(slot, "chaos_crash", r)
                    crashed_this_round.append(slot)
        for slot in sorted(self.active):
            if self.workers[slot].proc.poll() is not None:
                self._mark_left(slot, "exited", r)
                crashed_this_round.append(slot)
        if not self.active:
            raise QuorumError(f"round {r}: no active workers remain")
        stragglers = sorted(
            s for s in self.active
            if self.chaos is not None and self.chaos.straggler_mult(s) > 1.0)
        bcast = (self._write_bcast(r)
                 if self.params_avg is not None else None)
        report_paths: Dict[int, str] = {}
        dispatched: List[int] = []
        for slot in sorted(self.active):
            w = self.workers[slot]
            rp = os.path.join(self.workdir, f"rep_{r:06d}_w{slot}.npz")
            report_paths[slot] = rp
            cmd = {"cmd": "round", "round": r, "tau": self.tau,
                   "bcast": bcast, "report": rp}
            try:
                w.proc.stdin.write(json.dumps(cmd) + "\n")
                w.proc.stdin.flush()
                dispatched.append(slot)
            except (BrokenPipeError, ValueError, OSError):
                self._mark_left(slot, "pipe_closed", r)
                crashed_this_round.append(slot)
        # a planned straggler is preempted for the whole round: its
        # heartbeat stalls for real, and the exclusion set stays a pure
        # function of the FaultPlan (bitwise-replayable kill schedule)
        for slot in stragglers:
            if slot in self.active:
                try:
                    os.kill(self.workers[slot].proc.pid, signal.SIGSTOP)
                except (ProcessLookupError, OSError):
                    pass
        for slot in dispatched:
            self._watchdog.reset(slot)
        pending = [s for s in dispatched
                   if s in self.active and s not in stragglers]
        reports: Dict[int, dict] = {}
        dropped: Set[int] = set()
        drop_counted: Set[Any] = set()
        hb_missed: Set[int] = set()
        try:
            for attempt in range(self.max_retries + 1):
                if attempt > 0:
                    self.c_retries.inc()
                    self._event(kind="quorum_retry", round=r,
                                attempt=attempt,
                                have=sorted(reports), need=self.min_quorum)
                    time.sleep(self.backoff_s * attempt)
                t0 = prev = now_s()
                while pending:
                    for slot in list(pending):
                        w = self.workers[slot]
                        rp = report_paths[slot]
                        if os.path.exists(rp):
                            if (self.chaos is not None
                                    and self.chaos.drops(r, slot, attempt)):
                                # the report is "lost" for this whole
                                # attempt (the plan hash is stable per
                                # (round, slot, attempt)); a retry may
                                # redraw and accept it
                                if (slot, attempt) not in drop_counted:
                                    drop_counted.add((slot, attempt))
                                    self.c_dropped.inc()
                                dropped.add(slot)
                                continue
                            reports[slot] = self._read_report(rp)
                            dropped.discard(slot)
                            pending.remove(slot)
                        elif w.proc.poll() is not None:
                            self._mark_left(slot, "crashed_mid_round", r)
                            crashed_this_round.append(slot)
                            self.c_crashes.inc()
                            pending.remove(slot)
                    now = now_s()
                    self._hb_tick(pending, now - prev, hb_missed)
                    prev = now
                    if not pending or now - t0 >= self.deadline_s:
                        break
                    time.sleep(self.poll_s)
                if len(reports) >= self.min_quorum:
                    break
                # refill: a dropped report may clear on the next attempt,
                # and a late worker may still land its file
                pending = [s for s in dispatched
                           if s in self.active and s not in reports
                           and s not in stragglers]
            else:
                raise QuorumError(
                    f"round {r}: quorum {len(reports)}/{self.min_quorum} "
                    f"after {self.max_retries} retries "
                    f"(deadline {self.deadline_s}s; reported="
                    f"{sorted(reports)}, active={sorted(self.active)})")
        finally:
            for slot in stragglers:
                w = self.workers.get(slot)
                if w is not None and w.proc.poll() is None:
                    try:
                        os.kill(w.proc.pid, signal.SIGCONT)
                    except (ProcessLookupError, OSError):
                        pass
        late = [s for s in pending if s in self.active]
        included = sorted(reports)
        self.params_avg = masked_host_average(
            {s: reports[s]["params"] for s in included})
        loss = float(np.mean([reports[s]["loss"] for s in included]))
        self.iter_done = max(reports[s]["iter"] for s in included)
        self.rounds_done += 1
        self.losses.append(loss)
        self.c_rounds.inc()
        self.g_quorum.set(len(included))
        missing = sorted(set(dispatched) - set(included))
        self._event(kind="round", round=r, quorum=len(included),
                    included=included, missing=missing,
                    stragglers=stragglers,
                    crashed=sorted(set(crashed_this_round)),
                    late=late, dropped=sorted(dropped),
                    heartbeat_miss=sorted(hb_missed),
                    loss=round(loss, 8), iter=self.iter_done,
                    tau=self.tau,
                    wall_s=round(now_s() - t_round0, 6))
        if (self.snapshot_dir and self.snapshot_every > 0
                and self.rounds_done % self.snapshot_every == 0):
            self.snapshot()
        return loss

    def snapshot(self) -> Optional[str]:
        """Manifest-committed snapshot of the current quorum average
        (orbax_ckpt.save_step: temp+fsync+atomic replace, then the
        COMMIT manifest) — the artifact joins and supervisor restarts
        catch up from."""
        if self.snapshot_dir is None or self.params_avg is None:
            return None
        path = orbax_ckpt.save_step(self.snapshot_dir, self.rounds_done,
                                    self.iter_done, self.params_avg, {})
        self.c_snapshots.inc()
        self._event(kind="snapshot", step=self.rounds_done,
                    iter=self.iter_done, path=path)
        return path

    def run(self, n_rounds: int) -> List[float]:
        """Drive n_rounds, honoring SIGINT as snapshot-then-drain (and
        SIGHUP as snapshot-and-continue) via utils.signals — installed
        here unless the caller supplied its own action_source."""
        handler = self.action_source
        own: Optional[SignalHandler] = None
        if handler is None:
            try:
                own = SignalHandler(
                    sigint_effect=SolverAction.SNAPSHOT_STOP,
                    sighup_effect=SolverAction.SNAPSHOT).install()
                handler = own
            except ValueError:   # not the main thread: run un-handled
                handler = None
        losses: List[float] = []
        try:
            for _ in range(int(n_rounds)):
                losses.append(self.run_round())
                if handler is None:
                    continue
                action = handler.get_requested_action()
                if action is SolverAction.SNAPSHOT_STOP:
                    self.snapshot()
                    self._drain()
                    self._event(kind="sigint_snapshot_drain",
                                round=self.rounds_done)
                    break
                if action is SolverAction.STOP:
                    break
                if action is SolverAction.SNAPSHOT:
                    self.snapshot()
        finally:
            if own is not None:
                own.uninstall()
        return losses

    # ----------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        counters = {name: c.value
                    for name, c in [
                        ("worker_restarts", self.c_restarts),
                        ("heartbeat_miss", self.c_hb_miss),
                        ("proc_crashes", self.c_crashes),
                        ("proc_rounds", self.c_rounds),
                        ("quorum_retries", self.c_retries),
                        ("dropped_reports", self.c_dropped),
                        ("snapshots", self.c_snapshots)]}
        return {"rounds": self.rounds_done,
                "active_workers": sorted(self.active),
                "left": dict(self.left),
                "iter": self.iter_done,
                "restored_from": self._restored_from,
                "torn_snapshots_skipped": orbax_ckpt.torn_skipped_total(),
                **counters,
                "events": len(self.events)}
