"""Worker entrypoint for the process-level elastic supervisor.

One OS process = one SparkNet worker: it owns a single-chip Solver on its
data shard and runs τ local steps per round command, the role a Spark
executor's CaffeNet plays in the reference driver loop (reference:
CifarApp.scala:120-130 — foreachPartition step + collect weights), but
as a real preemptible process the supervisor can SIGKILL/SIGSTOP.

Protocol (line-oriented JSON, supervisor -> stdin / stdout -> supervisor):

  ready     {"ready": true, "slot": N, "restored_from": path|null,
             "iter": it}      — printed once after build (+ optional
                                snapshot catch-up restore)
  round cmd {"cmd": "round", "round": r, "tau": t,
             "bcast": path|null, "report": path}
  stop  cmd {"cmd": "stop"}

The worker NEVER writes to stdout after the ready line (an unread pipe
would eventually block a long run); per-round results travel through the
`report` npz, written tmp+fsync+`os.replace` so the supervisor can never
observe a torn report.  A broadcast file (`bcast`) carries the previous
round's quorum average; loading it re-syncs params (and the iteration
counter, so the lr schedule tracks the cohort) — which is also how a
SIGSTOP'd straggler rejoins the fold after SIGCONT.  Heartbeats are
file-mtime touches on `heartbeat_path` every `heartbeat_s` from a
daemon thread; they stall exactly while the process is stopped or dead,
which is what the supervisor's watchdog measures.

stdin EOF means the supervisor is gone: exit.  Chaos determinism note:
the worker itself draws no randomness beyond its seeded feed and the
solver's fold_in(iter) rng, so identical command schedules replay
bitwise (pinned by tests/test_elastic_proc.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time  # sleep only; timestamps flow through obs.trace.now_s


def _force_cpu() -> None:
    # the box's sitecustomize pre-imports jax, so the live-config update
    # is what actually takes effect (tests/conftest.py pattern)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")


def _build_toy(cfg: dict):
    """The chaos-toy net (scripts/chaos_run.py build_solver architecture)
    as a SINGLE-chip Solver: small enough that N worker processes compile
    and run inside the tier-1 budget."""
    import numpy as np

    import sparknet_tpu  # noqa: F401  (jax forward-compat graft)
    from ..core import layers_dsl as dsl
    from ..proto import caffe_pb
    from ..proto.textformat import parse
    from ..solver.solver import Solver

    batch = int(cfg.get("toy", {}).get("batch", 16))
    net = dsl.net_param(
        "proc_toy",
        dsl.memory_data_layer("data", ["data", "label"], batch=batch,
                              channels=1, height=4, width=4),
        dsl.inner_product_layer("ip1", "data", num_output=8),
        dsl.relu_layer("relu1", "ip1"),
        dsl.inner_product_layer("ip2", "ip1", num_output=2),
        dsl.softmax_with_loss_layer("loss", ["ip2", "label"]),
    )
    sp = caffe_pb.SolverParameter(parse(
        f"base_lr: 0.05 lr_policy: 'fixed' momentum: 0.9 "
        f"random_seed: {int(cfg.get('seed', 7))}"))
    solver = Solver(sp, net_param=net)
    rng = np.random.RandomState(1000 + int(cfg["slot"]))

    def src():
        x = rng.randn(batch, 1, 4, 4).astype(np.float32)
        return {"data": x,
                "label": (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)}

    solver.set_train_data(src)
    return solver


def _build_lenet(cfg: dict):
    """A REAL zoo net (train-form lenet) on the deploy subsystem's
    high-margin pattern stream (deploy/train_driver.synthetic_source):
    the proc-elastic trainer arm of the train-while-serve loop.  Each
    slot salts only the sign/noise stream (`noise_seed`) so shards are
    disjoint draws of the SAME task — the pattern direction comes from
    the shared seed, and averaging worker params stays constructive.
    lr 0.002 is the measured stable point (see deploy/train_driver.py).
    """
    import sparknet_tpu  # noqa: F401  (jax forward-compat graft)
    from ..deploy.train_driver import input_shape_of, synthetic_source
    from ..models import get_model
    from ..proto import caffe_pb
    from ..proto.textformat import parse
    from ..solver.solver import Solver

    sub = cfg.get("lenet", {})
    batch = int(sub.get("batch", 16))
    net = get_model("lenet", batch=batch, deploy=False)
    sp = caffe_pb.SolverParameter(parse(
        f"base_lr: {float(sub.get('lr', 0.002))} lr_policy: 'fixed' "
        f"momentum: 0.9 random_seed: {int(cfg.get('seed', 7))}"))
    solver = Solver(sp, net_param=net)
    solver.set_train_data(synthetic_source(
        input_shape_of(net), batch, int(sub.get("n_classes", 10)),
        int(cfg.get("seed", 7)), noise_seed=1000 + int(cfg["slot"])))
    return solver


def _build_solver_file(cfg: dict):
    """CLI proc mode: a real solver prototxt whose net self-feeds (the
    DataReader semantics — data/feeds.make_net_feeds); each worker seeds
    its stream by slot so shards are disjoint."""
    from ..data.feeds import make_net_feeds
    from ..proto import caffe_pb
    from ..solver.solver import Solver

    sp = caffe_pb.load_solver_prototxt(str(cfg["solver_path"]))
    solver = Solver(sp)
    feed = make_net_feeds(solver.net.net_param, "TRAIN",
                          seed=1000 + int(cfg["slot"]))
    if feed is None:
        raise ValueError(
            f"solver {cfg['solver_path']!r} has no self-feeding data "
            f"layer; proc-mode workers cannot share a --data batch list "
            f"across process boundaries")
    solver.set_train_data(feed)
    return solver


def _load_bcast(solver, path: str) -> None:
    import jax.numpy as jnp
    import numpy as np

    data = np.load(path)
    params = {k[len("param:"):]: jnp.asarray(data[k])
              for k in data.files if k.startswith("param:")}
    if params:
        solver.params = params
    if "__iter__" in data.files:
        solver.iter = int(data["__iter__"])


def _write_report(path: str, round_idx: int, solver, loss: float) -> None:
    """Atomic report publish: the supervisor polls for `path`, so its
    appearance must imply completeness (ipc.atomic_write_npz's
    tmp+fsync+os.replace)."""
    import numpy as np

    from .ipc import atomic_write_npz

    arrays = {f"param:{k}": np.asarray(v) for k, v in solver.params.items()}
    arrays["__loss__"] = np.float64(loss)
    arrays["__iter__"] = np.int64(solver.iter)
    arrays["__round__"] = np.int64(round_idx)
    atomic_write_npz(path, arrays)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="proc_worker")
    ap.add_argument("--config", required=True,
                    help="worker config JSON written by the supervisor")
    a = ap.parse_args(argv)
    with open(a.config) as f:
        cfg = json.load(f)
    _force_cpu()

    from .ipc import Heartbeat

    beat = None
    hb = cfg.get("heartbeat_path")
    if hb:
        beat = Heartbeat(hb, float(cfg.get("heartbeat_s", 0.25)))

    builder = cfg.get("builder", "toy")
    if builder == "toy":
        solver = _build_toy(cfg)
    elif builder == "lenet":
        solver = _build_lenet(cfg)
    elif builder == "solver":
        solver = _build_solver_file(cfg)
    else:
        raise ValueError(f"unknown proc worker builder {builder!r} "
                         f"(expected 'toy', 'lenet', or 'solver')")

    restored = None
    root = cfg.get("restore_root")
    if root:
        from ..utils.orbax_ckpt import resolve_latest, restore_auto

        src = resolve_latest(root)
        if src is not None:
            import jax.numpy as jnp

            it, params, _state = restore_auto(src)
            solver.params = {k: jnp.asarray(v) for k, v in params.items()}
            solver.iter = int(it)
            restored = src

    print(json.dumps({"ready": True, "slot": int(cfg["slot"]),
                      "restored_from": restored,
                      "iter": int(solver.iter)}), flush=True)

    sleep_s = float(cfg.get("round_sleep_s", 0.0))
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            cmd = json.loads(line)
        except ValueError:
            print(f"proc_worker[{cfg['slot']}]: malformed command "
                  f"{line!r}", file=sys.stderr, flush=True)
            continue
        kind = cmd.get("cmd")
        if kind == "stop":
            break
        if kind != "round":
            continue
        if cmd.get("bcast"):
            _load_bcast(solver, cmd["bcast"])
        if sleep_s > 0.0:
            time.sleep(sleep_s)  # test knob: widen the mid-round window
        loss = solver.step(int(cmd.get("tau", cfg.get("tau", 1))))
        _write_report(cmd["report"], int(cmd["round"]), solver, loss)
    if beat is not None:
        beat.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
