"""Adaptive-τ controller: move the averaging interval with the observed
straggler-stall / communication balance.

SparkNet's tradeoff (PAPER.md; the paper's §3 analysis): larger τ
amortizes each synchronization over more local steps — exactly what you
want when waiting on stragglers (stall) dominates the cost of a round —
but too-large τ slows convergence per iteration.  The controller grows τ
(doubling, like TCP slow-start in reverse) while stall dominates the
communication cost for `patience` consecutive rounds, shrinks it back
(halving) when rounds are balanced, and always clamps to
[tau_min, tau_max].  Inputs are taken from round telemetry
(DistributedSolver.round_stats() / the elastic runtime's simulated stall
clock), never wall-clock direct, so controller trajectories are
deterministic in tests.
"""

from __future__ import annotations


class AdaptiveTau:
    """Hysteretic doubling/halving controller over τ.

    update(stall_s, comm_s) returns the τ to use NEXT round:
      ratio = stall_s / max(comm_s, eps)
      ratio > grow_ratio  for `patience` consecutive rounds -> τ *= 2
      ratio < shrink_ratio for `patience` consecutive rounds -> τ //= 2
    clamped to [tau_min, tau_max].  The consecutive-round hysteresis is
    what keeps one noisy round from flapping τ (and recompiling the
    round program) — the round-fn cache in DistributedSolver makes an
    oscillation cheap anyway, but a stable τ keeps the telemetry legible.
    """

    def __init__(self, tau0: int, *, tau_min: int = 1, tau_max: int = 64,
                 grow_ratio: float = 1.0, shrink_ratio: float = 0.25,
                 patience: int = 2) -> None:
        if tau_min < 1:
            raise ValueError(f"tau_min must be >= 1, got {tau_min}")
        if tau_max < tau_min:
            raise ValueError(f"tau_max ({tau_max}) < tau_min ({tau_min})")
        if shrink_ratio >= grow_ratio:
            raise ValueError(
                f"shrink_ratio ({shrink_ratio}) must be below grow_ratio "
                f"({grow_ratio}) — equal thresholds flap")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        self.tau_min, self.tau_max = int(tau_min), int(tau_max)
        self.grow_ratio, self.shrink_ratio = grow_ratio, shrink_ratio
        self.patience = int(patience)
        self.tau = min(max(int(tau0), self.tau_min), self.tau_max)
        self._hi = 0
        self._lo = 0

    def update(self, stall_s: float, comm_s: float) -> int:
        ratio = float(stall_s) / max(float(comm_s), 1e-9)
        if ratio > self.grow_ratio:
            self._hi, self._lo = self._hi + 1, 0
        elif ratio < self.shrink_ratio:
            self._hi, self._lo = 0, self._lo + 1
        else:
            self._hi = self._lo = 0
        if self._hi >= self.patience:
            self._hi = 0
            self.tau = min(self.tau * 2, self.tau_max)
        elif self._lo >= self.patience:
            self._lo = 0
            self.tau = max(self.tau // 2, self.tau_min)
        return self.tau
