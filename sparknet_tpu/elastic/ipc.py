"""Shared OS-process plumbing for supervisor-style subsystems.

PR 12's ProcSupervisor (elastic/proc.py) solved the hard subprocess
problems once — spawn with a pinned-CPU environment, one-ready-line
handshake with a stderr tail on failure, file-mtime heartbeats with a
supervisor-side stall watchdog, atomic tmp+fsync+replace publishes, and
a SIGCONT -> polite stop -> terminate -> kill drain ladder.  The serving
fleet router (serving/fleet.py) needs exactly the same mechanics, so
this module factors them out of proc.py instead of growing a second
copy.

On top of the line-JSON handshake it adds a binary FRAME protocol for
request/response traffic that carries arrays (the serving payload):

    frame := b"SNF1" | u64-le payload length | payload
    payload := np.savez archive; "__meta__" holds the JSON header
               (utf-8 bytes as a uint8 array), every other key is a
               payload array

A frame is built fully in memory and written with ONE write()+flush(),
so concurrent writers serialized by a lock can never interleave bytes
(atomic framing); the reader does exact-count reads and dies with a
stream-naming ValueError on desync (the R002 parser contract) or
IpcClosed on EOF — never struct.error.

Everything here is transport: no jax, no model code, importable from a
worker before its platform is configured.
"""

from __future__ import annotations

import io
import json
import os
import select
import signal
import struct
import subprocess
import sys
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..obs.trace import now_s

__all__ = [
    "REPO_ROOT", "IpcError", "IpcClosed", "worker_env", "spawn_worker",
    "stderr_tail", "wait_ready_line", "write_frame", "read_frame",
    "touch", "Heartbeat", "MtimeWatchdog", "atomic_write_npz",
    "sigcont", "reap",
]

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

FRAME_MAGIC = b"SNF1"
_FRAME_HEAD = struct.Struct("<4sQ")
MAX_FRAME_BYTES = 1 << 31   # desync tripwire, not a real payload bound


class IpcError(Exception):
    """Transport-level failure talking to a worker process."""


class IpcClosed(IpcError):
    """The peer hung up (EOF / broken pipe) — distinct from a malformed
    stream, which is a ValueError like every other parser in the tree."""


# ------------------------------------------------------------------ spawn
def worker_env(extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Child environment: CPU-pinned jax (the box's sitecustomize
    pre-imports jax, so the env var must be set before the child starts)
    plus the repo root on PYTHONPATH so `-m sparknet_tpu...` resolves
    from any cwd."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if extra:
        env.update(extra)
    return env


def spawn_worker(module: str, cfg_path: str, *, stderr_f,
                 env: Optional[Dict[str, str]] = None,
                 text: bool = True) -> subprocess.Popen:
    """Launch `python -m <module> --config <cfg_path>` as a supervised
    worker.  start_new_session detaches it from the terminal's process
    group: a ctrl-C reaches ONLY the supervisor, which then drains
    instead of every child dying mid-work.  text=False selects binary
    std streams for frame traffic (serving fleet); the ready line works
    either way.  The guaranteed kill path for these processes is
    reap() below."""
    return subprocess.Popen(
        [sys.executable, "-m", module, "--config", cfg_path],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=stderr_f,
        text=text, bufsize=(1 if text else -1),
        start_new_session=True, env=env or worker_env())


def stderr_tail(path: str, n: int = 2000) -> str:
    """Last `n` bytes of a worker's stderr file — the diagnostic payload
    for spawn/ready failures."""
    try:
        with open(path, "rb") as f:
            f.seek(max(0, os.path.getsize(path) - n))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""


def wait_ready_line(proc: subprocess.Popen, *, timeout_s: float,
                    what: str = "worker",
                    stderr_path: Optional[str] = None) -> dict:
    """Block (bounded) until the child prints its one JSON ready line on
    stdout; returns the parsed message.  Works for text and binary
    stdout (the ready line is the first line either way).  Raises
    RuntimeError with the stderr tail when the child dies or stays
    silent past timeout_s."""
    t0 = now_s()
    while True:
        remaining = timeout_s - (now_s() - t0)
        if remaining <= 0:
            break
        r, _, _ = select.select([proc.stdout], [], [],
                                min(remaining, 0.5))
        if not r:
            if proc.poll() is not None:
                break
            continue
        line = proc.stdout.readline()
        if not line:
            break
        if isinstance(line, bytes):
            line = line.decode("utf-8", "replace")
        try:
            msg = json.loads(line)
        except ValueError:
            continue
        if msg.get("ready"):
            return msg
    tail = stderr_tail(stderr_path) if stderr_path else ""
    raise RuntimeError(
        f"{what} (pid {proc.pid}) never reported ready within "
        f"{timeout_s:.0f}s (rc={proc.poll()}); stderr tail:\n{tail}")


# ----------------------------------------------------------------- frames
def write_frame(stream, meta: Dict[str, Any],
                arrays: Optional[Dict[str, np.ndarray]] = None, *,
                lock: Optional[threading.Lock] = None) -> None:
    """Serialize one frame and publish it with a single write()+flush().
    `lock` (when given) serializes concurrent writers onto one pipe —
    combined with the one-write publish, frames can never interleave."""
    payload_arrays: Dict[str, np.ndarray] = dict(arrays or {})
    payload_arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **payload_arrays)
    payload = buf.getvalue()
    frame = _FRAME_HEAD.pack(FRAME_MAGIC, len(payload)) + payload
    try:
        if lock is not None:
            with lock:
                stream.write(frame)
                stream.flush()
        else:
            stream.write(frame)
            stream.flush()
    except (BrokenPipeError, ValueError, OSError) as e:
        raise IpcClosed(f"peer pipe closed while writing frame: {e}")


def _read_exact(stream, n: int, what: str, *, got_any: bool) -> bytes:
    chunks = []
    have = 0
    while have < n:
        try:
            b = stream.read(n - have)
        except (OSError, ValueError) as e:
            raise IpcClosed(f"{what}: pipe error mid-frame: {e}")
        if not b:
            if have == 0 and not got_any:
                raise IpcClosed(f"{what}: EOF")
            raise IpcClosed(
                f"{what}: EOF after {have}/{n} frame bytes (torn frame)")
        chunks.append(b)
        have += len(b)
    return b"".join(chunks)


def read_frame(stream, *, what: str = "peer"
               ) -> Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]]:
    """Read one frame; returns (meta, arrays), or None on a clean EOF at
    a frame boundary (the peer exited).  A desynchronized or malformed
    stream dies with a ValueError naming `what` (never struct.error /
    zipfile noise); a mid-frame hangup raises IpcClosed."""
    try:
        head = _read_exact(stream, _FRAME_HEAD.size, what, got_any=False)
    except IpcClosed as e:
        if str(e).endswith("EOF"):
            return None
        raise
    try:
        magic, length = _FRAME_HEAD.unpack(head)
    except struct.error as e:        # unreachable with exact reads
        raise ValueError(f"{what}: unreadable frame header: {e}")
    if magic != FRAME_MAGIC:
        raise ValueError(
            f"{what}: bad IPC frame magic {magic!r} (expected "
            f"{FRAME_MAGIC!r}; stream desynchronized)")
    if length > MAX_FRAME_BYTES:
        raise ValueError(
            f"{what}: implausible frame length {length} "
            f"(> {MAX_FRAME_BYTES}; stream desynchronized)")
    payload = _read_exact(stream, length, what, got_any=True)
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as data:
            if "__meta__" not in data.files:
                raise KeyError("__meta__")
            meta = json.loads(bytes(data["__meta__"].tobytes())
                              .decode("utf-8"))
            arrays = {k: np.array(data[k]) for k in data.files
                      if k != "__meta__"}
    except Exception as e:   # zipfile / pickle-refusal / json / key errors
        raise ValueError(f"{what}: malformed frame payload "
                         f"({type(e).__name__}: {e})")
    if not isinstance(meta, dict):
        raise ValueError(f"{what}: frame meta is {type(meta).__name__}, "
                         f"expected an object")
    return meta, arrays


# -------------------------------------------------------------- heartbeat
def touch(path: str) -> None:
    with open(path, "a"):
        pass
    os.utime(path, None)


class Heartbeat:
    """Worker-side file-mtime heartbeat on a daemon thread
    (proc_worker's `_beat` pattern): touches `path` every `period_s`,
    which stalls exactly while the process is SIGSTOP'd or dead — the
    signal the supervisor's MtimeWatchdog measures."""

    def __init__(self, path: str, period_s: float) -> None:
        self.path = path
        self.period_s = float(period_s)
        touch(path)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sparknet-heartbeat")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                touch(self.path)
            except OSError:
                return

    def stop(self, join_timeout_s: float = 2.0) -> None:
        """Bounded: the loop wakes on the event within one period; the
        timeout only caps a touch stuck on a dead filesystem."""
        self._stop.set()
        self._thread.join(timeout=join_timeout_s)


class MtimeWatchdog:
    """Supervisor-side heartbeat-stall detector (ProcSupervisor's
    `_hb_tick` logic, keyed): tracks each key's last observed mtime
    signature and accumulates supervisor-clock stall time while it
    doesn't move.  tick() returns True exactly once per stall episode,
    when the accumulated stall first crosses `miss_after_s`."""

    def __init__(self, miss_after_s: float) -> None:
        self.miss_after_s = float(miss_after_s)
        self._sig: Dict[Any, Any] = {}
        self._stall: Dict[Any, float] = {}
        self._fired: Dict[Any, bool] = {}

    def reset(self, key) -> None:
        """Forget a key's state (fresh spawn / fresh dispatch)."""
        self._sig.pop(key, None)
        self._stall.pop(key, None)
        self._fired.pop(key, None)

    def stalled_s(self, key) -> float:
        return self._stall.get(key, 0.0)

    def tick(self, key, path: str, dt: float) -> bool:
        try:
            sig = (os.stat(path).st_mtime_ns,)
        except OSError:
            sig = None
        if sig != self._sig.get(key, ()):
            self._sig[key] = sig
            self._stall[key] = 0.0
            self._fired[key] = False
            return False
        self._stall[key] = self._stall.get(key, 0.0) + dt
        if (self._stall[key] > self.miss_after_s
                and not self._fired.get(key)):
            self._fired[key] = True
            return True
        return False


# --------------------------------------------------------------- publish
def atomic_write_npz(path: str, arrays: Dict[str, np.ndarray]) -> None:
    """tmp + fsync + os.replace publish: the file's appearance implies
    completeness, so a poller can never observe a torn archive
    (proc_worker's `_write_report` discipline)."""
    tmp = os.path.join(os.path.dirname(os.path.abspath(path)),
                       f".tmp.{os.getpid()}.{os.path.basename(path)}")
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ------------------------------------------------------------------ reap
def sigcont(pid: int) -> None:
    """Wake a possibly-SIGSTOP'd child so it can process a stop command
    (a stopped process cannot drain)."""
    try:
        os.kill(pid, signal.SIGCONT)
    except (ProcessLookupError, OSError):
        pass


def reap(proc: subprocess.Popen, *, wait_s: float = 5.0) -> None:
    """Bounded terminate-then-kill ladder for a child that already got
    its polite stop command: wait, terminate, kill — every Popen this
    module spawns funnels through here, so no supervisor leaks
    children."""
    if proc.poll() is not None:
        return
    try:
        proc.wait(timeout=wait_s)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=2)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=wait_s)
            except subprocess.TimeoutExpired:
                pass
