"""Elastic training runtime: partial-quorum rounds over a fixed worker
mesh, slot-based join/leave with snapshot catch-up, deterministic fault
injection, and an adaptive-τ controller.  See runtime.py for the design
and the simulation/time model that makes every behavior testable on the
8-virtual-device CPU mesh."""

from .chaos import FaultPlan
from .runtime import ElasticRuntime, QuorumError, ShardedFeed
from .tau import AdaptiveTau

__all__ = ["AdaptiveTau", "ElasticRuntime", "FaultPlan", "QuorumError",
           "ShardedFeed"]
