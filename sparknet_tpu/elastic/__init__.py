"""Elastic training runtime: partial-quorum rounds over a fixed worker
mesh, slot-based join/leave with snapshot catch-up, deterministic fault
injection, and an adaptive-τ controller.  See runtime.py for the design
and the simulation/time model that makes every behavior testable on the
8-virtual-device CPU mesh — and proc.py for the process-level supervisor
that graduates the same algebra to REAL preemption (worker subprocesses,
SIGKILL/SIGSTOP chaos, wall-clock deadlines, manifest-validated snapshot
catch-up).

ProcSupervisor is imported lazily (module attribute) so `from
sparknet_tpu.elastic import FaultPlan` stays cheap in worker processes.
"""

from .chaos import FaultPlan
from .runtime import ElasticRuntime, QuorumError, ShardedFeed
from .tau import AdaptiveTau

__all__ = ["AdaptiveTau", "ElasticRuntime", "FaultPlan", "ProcSupervisor",
           "QuorumError", "ShardedFeed", "masked_host_average"]


def __getattr__(name):
    if name in ("ProcSupervisor", "masked_host_average"):
        from . import proc

        return getattr(proc, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
