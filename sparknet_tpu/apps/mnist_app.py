"""LeNet on MNIST built through the programmatic DSL — the reference's
"Scala NetParam DSL" config (reference: src/test/scala/libs/LayerSpec.scala:
20-35 builds LeNet via the DSL; examples/mnist/lenet_solver.prototxt drives
training).

    python -m sparknet_tpu.apps.mnist_app [--data DIR] [--iterations N]
        [--synthetic]
"""

from __future__ import annotations

import argparse
from typing import Optional

import numpy as np

from ..core import layers_dsl as dsl
from ..data.mnist import load_mnist
from ..data import partition as part
from ..proto import caffe_pb
from ..solver.solver import Solver
from ..utils.logging import PhaseLogger

BATCH = 64


def lenet(batch: int = BATCH) -> "caffe_pb.NetParameter":
    """LeNet via the DSL (mirrors examples/mnist/lenet_train_test.prototxt)."""
    return dsl.net_param(
        "LeNet",
        dsl.memory_data_layer("mnist", ["data", "label"], batch=batch,
                              channels=1, height=28, width=28),
        dsl.convolution_layer("conv1", "data", num_output=20, kernel_size=5,
                              weight_filler="xavier"),
        dsl.pooling_layer("pool1", "conv1", pool="MAX", kernel_size=2,
                          stride=2),
        dsl.convolution_layer("conv2", "pool1", num_output=50, kernel_size=5,
                              weight_filler="xavier"),
        dsl.pooling_layer("pool2", "conv2", pool="MAX", kernel_size=2,
                          stride=2),
        dsl.inner_product_layer("ip1", "pool2", num_output=500,
                                weight_filler="xavier"),
        dsl.relu_layer("relu1", "ip1"),
        dsl.inner_product_layer("ip2", "ip1", num_output=10,
                                weight_filler="xavier"),
        dsl.softmax_with_loss_layer("loss", ["ip2", "label"]),
        dsl.accuracy_layer("accuracy", ["ip2", "label"], phase="TEST"),
    )


def lenet_solver() -> "caffe_pb.SolverParameter":
    """(mirrors examples/mnist/lenet_solver.prototxt)"""
    return dsl.solver_param(base_lr=0.01, lr_policy="inv", momentum=0.9,
                            weight_decay=0.0005, max_iter=10000,
                            solver_type="SGD", random_seed=1,
                            gamma=0.0001, power=0.75)


def synthetic_mnist(n=2000, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int32)
    imgs = rng.randint(0, 50, size=(n, 1, 28, 28))
    for i in range(n):
        r = labels[i]
        imgs[i, 0, 2 * r:2 * r + 3, :] += 180
    return np.clip(imgs, 0, 255).astype(np.uint8), labels


def run(*, data_dir: str = "", iterations: int = 1000, batch: int = BATCH,
        synthetic: bool = False, log_path: Optional[str] = None) -> float:
    log = PhaseLogger(log_path)
    try:
        return _run(log, data_dir=data_dir, iterations=iterations,
                    batch=batch, synthetic=synthetic)
    finally:
        log.close()


def _run(log, *, data_dir, iterations, batch, synthetic) -> float:
    if synthetic or not data_dir:
        xtr, ytr = synthetic_mnist()
        xte, yte = synthetic_mnist(500, seed=9)
    else:
        xtr, ytr = load_mnist(data_dir, "train")
        xte, yte = load_mnist(data_dir, "test")
    solver = Solver(lenet_solver(), net_param=lenet(batch))
    train = part.make_minibatches(xtr.astype(np.float32) / 256.0, ytr, batch)
    test = part.make_minibatches(xte.astype(np.float32) / 256.0, yte, batch)
    i = [0]

    def train_src():
        b = train[i[0] % len(train)]
        i[0] += 1
        return {"data": b[0], "label": b[1]}

    j = [0]

    def test_src():
        b = test[j[0] % len(test)]
        j[0] += 1
        return {"data": b[0], "label": b[1]}

    solver.set_train_data(train_src)
    solver.set_test_data(test_src, len(test))
    done = 0
    while done < iterations:
        chunk = min(100, iterations - done)
        loss = solver.step(chunk)
        done = solver.iter
        log(f"loss = {loss}", i=done)
    scores = solver.test()
    log(f"test accuracy = {scores.get('accuracy')}")
    return float(scores.get("accuracy", 0.0))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data", default="")
    p.add_argument("--iterations", type=int, default=1000)
    p.add_argument("--synthetic", action="store_true")
    a = p.parse_args()
    acc = run(data_dir=a.data, iterations=a.iterations, synthetic=a.synthetic)
    print(f"final accuracy: {acc}")


if __name__ == "__main__":
    main()
