"""ImageNetApp: distributed AlexNet/CaffeNet training from tar shards
(reference: src/main/scala/apps/ImageNetApp.scala).

Flow parity (:25-189): list shards -> per-worker shard assignment -> decode/
resize to 256x256 -> mean image -> per-round sampling with train-time random
227-crop + mean subtraction and test-time center crop (:124-138) -> τ=50
local steps + weight averaging (:151) -> top-1 scoring.

    python -m sparknet_tpu.apps.imagenet_app N --shards DIR --labels FILE
        [--model alexnet|caffenet] [--synthetic]
"""

from __future__ import annotations

import argparse
import os
import time
from typing import List, Optional

import numpy as np

from ..data import partition as part
from ..data.imagenet import ImageNetLoader, shard_paths_for_worker
from ..data.transform import DataTransformer
from ..parallel.dist import DistributedSolver
from ..proto import caffe_pb
from ..utils.logging import PhaseLogger

# (reference: ImageNetApp.scala:20-26)
TRAIN_BATCH_SIZE = 256
TEST_BATCH_SIZE = 50
FULL_HEIGHT, FULL_WIDTH = 256, 256
CROPPED = 227
SYNC_INTERVAL = 50  # τ (ImageNetApp.scala:151)

MODEL_PROTO = {
    "alexnet": "/root/reference/caffe/models/bvlc_alexnet",
    "caffenet": "/root/reference/caffe/models/bvlc_reference_caffenet",
    "googlenet": "/root/reference/caffe/models/bvlc_googlenet",
}


def build_solver(model: str, n_workers: int, tau: int, batch_size: int,
                 test_batch: int, mesh=None, crop: int = CROPPED,
                 dcn_interval: int = 1, mean_image=None,
                 device_transform: bool = False, scan_unroll=1,
                 sync_history: str = "local",
                 base_lr: Optional[float] = None) -> DistributedSolver:
    """device_transform: fuse the crop/mirror/mean pipeline into the
    compiled round (ops/device_transform.py) — feeds then ship raw uint8
    256x256 images, 4x less host->device traffic and no host transform
    loop (the TPU-native data-path split, BENCH_NOTES.md).
    scan_unroll/sync_history pass through to DistributedSolver (CPU-mesh
    studies and the momentum-at-sync option, dist.py docstring — keep
    the "local" default at this app's τ=50; switch to "average" only
    for small-τ experiments, where local momentum measurably interferes);
    base_lr overrides the solver prototxt's lr BEFORE construction
    (downscaled-batch studies applying the linear scaling rule)."""
    d = MODEL_PROTO[model]
    net = caffe_pb.load_net_prototxt(os.path.join(d, "train_val.prototxt"))
    net = caffe_pb.replace_data_layers(net, batch_size, test_batch, 3, crop,
                                       crop)
    sp = caffe_pb.load_solver_prototxt_with_net(
        os.path.join(d, "solver.prototxt"), net)
    if base_lr is not None:
        sp.msg.set("base_lr", float(base_lr))
    dt = dte = None
    if device_transform:
        from ..ops.device_transform import make_device_transformer

        dt = make_device_transformer(crop_size=crop, mirror=True,
                                     mean_image=mean_image, phase="TRAIN")
        dte = make_device_transformer(crop_size=crop, mean_image=mean_image,
                                      phase="TEST")
    return DistributedSolver(sp, n_workers=n_workers, tau=tau, mesh=mesh,
                             dcn_interval=dcn_interval, device_transform=dt,
                             device_transform_eval=dte,
                             scan_unroll=scan_unroll,
                             sync_history=sync_history)


class ShardFeed:
    """Streams this worker's tar shards through decode (-> host transform
    when one is given; raw uint8 otherwise, for the device-transform
    path); loops forever (the reference re-runs partitions each round)."""

    def __init__(self, loader: ImageNetLoader, shards: List[str],
                 label_file: str, batch_size: int,
                 transformer: Optional[DataTransformer]) -> None:
        self.loader = loader
        self.shards = shards
        self.label_file = label_file
        self.batch_size = batch_size
        self.transformer = transformer
        self._it = None

    def _fresh(self):
        return self.loader.batches(self.label_file,
                                   batch_size=self.batch_size,
                                   height=FULL_HEIGHT, width=FULL_WIDTH,
                                   shards=self.shards)

    def __call__(self):
        if self._it is None:
            self._it = self._fresh()
        try:
            imgs, labels = next(self._it)
        except StopIteration:
            self._it = self._fresh()
            imgs, labels = next(self._it)
        if self.transformer is None:
            return {"data": imgs, "label": labels}  # raw uint8, on-device tf
        return {"data": self.transformer(imgs), "label": labels}


def synthetic_feed(batch_size: int, crop: int, n_classes: int = 1000,
                   seed: int = 0):
    rng = np.random.RandomState(seed)

    def source():
        return {"data": rng.rand(batch_size, 3, crop, crop)
                .astype(np.float32),
                "label": rng.randint(0, n_classes, size=(batch_size,))
                .astype(np.int32)}

    return source


def run(num_workers: int, *, shards_dir: str = "", label_file: str = "",
        model: str = "alexnet", rounds: int = 100, synthetic: bool = False,
        batch_size: int = TRAIN_BATCH_SIZE, tau: int = SYNC_INTERVAL,
        test_batch: int = TEST_BATCH_SIZE, mesh=None,
        log_path: Optional[str] = None, crop: int = CROPPED,
        test_every: int = 10, dcn_interval: int = 1,
        snapshot_every_rounds: int = 0, snapshot_prefix: str = "",
        resume: str = "", device_transform: Optional[bool] = None) -> float:
    """device_transform (default: on for real data): ship raw uint8 from
    the shard feeds and run crop/mirror/mean inside the compiled round —
    the TPU-native data path (BENCH_NOTES.md); off falls back to the
    host-side DataTransformer."""
    log = PhaseLogger(log_path or
                      f"/tmp/training_log_{int(time.time())}.txt")
    try:
        log(f"workers = {num_workers}, model = {model}, tau = {tau}")
        if device_transform is None:
            device_transform = not (synthetic or not shards_dir)

        if synthetic or not shards_dir:
            if device_transform:
                # the synthetic feed produces pre-transformed crops, so there
                # is nothing for a device transform to do — don't pretend
                raise SystemExit(
                    "--device-transform needs real shard data "
                    "(the synthetic feed is already crop-sized floats)")
            solver = build_solver(model, num_workers, tau, batch_size,
                                  test_batch, mesh=mesh, crop=crop,
                                  dcn_interval=dcn_interval)
            log("built solver")
            feeds = [synthetic_feed(batch_size, crop, seed=w)
                     for w in range(num_workers)]
            test_source = synthetic_feed(test_batch, crop, seed=999)
            num_test = 2
        else:
            loader = ImageNetLoader(shards_dir)
            paths = loader.get_file_paths()
            # mean image over a sample (reference computes the full distributed
            # mean, ImageNetApp.scala:95-105 / ComputeMean.scala)
            from ..data.transform import compute_mean_image
            sample = loader.batches(label_file, batch_size=batch_size,
                                    shards=paths[:1])
            mean = compute_mean_image(b for b, _ in [next(sample)])
            log("computed mean image")
            solver = build_solver(model, num_workers, tau, batch_size,
                                  test_batch, mesh=mesh, crop=crop,
                                  dcn_interval=dcn_interval, mean_image=mean,
                                  device_transform=device_transform)
            log("built solver")
            if device_transform:
                train_tf = test_tf = None  # raw uint8; transform on device
                log("device-side transform enabled (uint8 feed)")
            else:
                train_tf = DataTransformer(crop_size=crop, mirror=True,
                                           mean_image=mean, phase="TRAIN")
                test_tf = DataTransformer(crop_size=crop, mean_image=mean,
                                          phase="TEST")
            feeds = [ShardFeed(loader, shard_paths_for_worker(paths, w,
                                                              num_workers),
                               label_file, batch_size, train_tf)
                     for w in range(num_workers)]
            test_source = ShardFeed(loader, paths, label_file, test_batch,
                                    test_tf)
            num_test = 10
            solver.set_prefetch(True)  # stream feeds: stage N+1 during N
        solver.set_train_data(feeds)
        solver.set_test_data(test_source, num_test)

        from .common import (check_snapshot_args, maybe_snapshot_round,
                             resume_and_replay)
        check_snapshot_args(snapshot_every_rounds, snapshot_prefix)
        start_round = 0
        if resume:
            start_round = resume_and_replay(solver, resume, feeds, log)

        accuracy = 0.0
        for r in range(start_round, rounds):
            if r % test_every == 0:
                scores = solver.test()
                accuracy = scores.get("accuracy", 0.0)
                if "loss" in scores:  # test-net loss, for plot types 2/3
                    log(f"test loss = {scores['loss']}", i=r)
                log(f"%-age of test set correct: {accuracy}", i=r)
            log("starting training", i=r)
            loss = solver.run_round(prefetch_next=r < rounds - 1)
            log(f"round lr = "
                f"{solver.current_lr():.8g}", i=r)
            log(f"round loss = {loss}", i=r)
            maybe_snapshot_round(solver, log, r, snapshot_every_rounds,
                                 snapshot_prefix)
        scores = solver.test()
        accuracy = scores.get("accuracy", 0.0)
        if "loss" in scores:
            log(f"test loss = {scores['loss']}")
        log(f"final %-age of test set correct: {accuracy}")
        return accuracy
    finally:
        log.close()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("num_workers", type=int)
    p.add_argument("--shards", default="")
    p.add_argument("--labels", default="")
    p.add_argument("--model", default="alexnet", choices=list(MODEL_PROTO))
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--device-transform", dest="device_transform",
                   action="store_true", default=None,
                   help="augment on device from raw uint8 feeds "
                        "(default: on for real data)")
    p.add_argument("--no-device-transform", dest="device_transform",
                   action="store_false")
    from ..utils.compile_cache import (apply_platform_env,
                                      maybe_enable_compile_cache)
    from .common import (add_distributed_args, add_snapshot_args,
                         mesh_from_args)

    apply_platform_env()
    maybe_enable_compile_cache()
    add_distributed_args(p, batch_default=TRAIN_BATCH_SIZE,
                         tau_default=SYNC_INTERVAL)
    add_snapshot_args(p)
    a = p.parse_args()
    mesh = mesh_from_args(a)
    run(a.num_workers, shards_dir=a.shards, label_file=a.labels,
        model=a.model, rounds=a.rounds, synthetic=a.synthetic, mesh=mesh,
        dcn_interval=a.dcn_interval, batch_size=a.batch, tau=a.tau,
        snapshot_every_rounds=a.snapshot_every_rounds,
        snapshot_prefix=a.snapshot_prefix, resume=a.resume,
        device_transform=a.device_transform)


if __name__ == "__main__":
    main()
