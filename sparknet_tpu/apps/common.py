"""Shared distributed-flags plumbing for the app entry points.

One place for the --multihost/--slices/--dcn-interval surface so every app
validates the same way (the reference apps share their driver loop shape the
same way, CifarApp.scala vs ImageNetApp.scala).
"""

from __future__ import annotations

from typing import Optional


def add_distributed_args(p, *, batch_default: int,
                         tau_default: int) -> None:
    p.add_argument("--multihost", action="store_true",
                   help="jax.distributed bring-up (call on every TPU-VM "
                        "worker; auto-detects on Cloud TPU)")
    p.add_argument("--slices", type=int, default=1,
                   help=">1 uses a (dcn, workers) hierarchical mesh")
    p.add_argument("--dcn-interval", type=int, default=1,
                   help="cross-slice average every k-th round")
    p.add_argument("--batch", type=int, default=batch_default)
    p.add_argument("--tau", type=int, default=tau_default,
                   help="local SGD steps between weight averages")


def add_snapshot_args(p) -> None:
    """App-level periodic checkpointing of the averaged weights + per-worker
    solver state (SURVEY.md §5.4 — realizing the reference's dead
    driver-checkpoint code, CifarDBApp.scala:144-149)."""
    p.add_argument("--snapshot-every-rounds", type=int, default=0,
                   help="write a snapshot every N averaging rounds")
    p.add_argument("--snapshot-prefix", default="",
                   help="snapshot path prefix (files: "
                        "<prefix>_iter_<N>.npz)")
    p.add_argument("--resume", default="",
                   help="snapshot file to resume from")


def check_snapshot_args(every: int, prefix: str) -> None:
    """Fail fast on a half-configured snapshot request instead of silently
    writing nothing for the whole run."""
    if every and not prefix:
        raise SystemExit(
            "--snapshot-every-rounds needs --snapshot-prefix")


def maybe_snapshot_round(solver, log, r: int, every: int,
                         prefix: str) -> Optional[str]:
    """Post-round hook: snapshot after rounds every, 2*every, ...  Returns
    the written path (averaged weights + full per-worker momentum, so a
    kill-and-resume run reproduces the uninterrupted one exactly)."""
    if every and prefix and (r + 1) % every == 0:
        path = solver.snapshot(f"{prefix}_iter_{solver.iter}")
        log(f"snapshot -> {path}", i=r)
        return path
    return None


def resume_and_replay(solver, resume_path: str, feeds, log,
                      per_round=None) -> int:
    """Restore the solver, then replay each feed's data stream through the
    already-consumed rounds so RNG/iterator state matches the uninterrupted
    run (the reference relies on Spark re-running partitions
    deterministically for the same effect).  `per_round(feed)` runs any
    per-round feed reset the app's loop would have done (e.g.
    WorkerFeed.new_round).  Returns the round to continue from."""
    solver.restore(resume_path)
    start = solver.round
    # round-major, matching run_round's consumption order exactly — feeds
    # may share host state (e.g. the ImageNet apps share one stateful
    # DataTransformer RNG across workers), so replay order matters
    for _ in range(start):
        for f in feeds:
            if per_round is not None:
                per_round(f)
            for _ in range(solver.tau):
                f()
    log(f"resumed from {resume_path} at round {start} (iter {solver.iter})")
    return start


def mesh_from_args(a) -> Optional[object]:
    """Validate the flag combination and build the mesh (None = flat
    default).  Fail fast at parse time, not deep inside the solver."""
    if a.dcn_interval != 1 and a.slices <= 1:
        raise SystemExit("--dcn-interval needs --slices > 1")
    if a.multihost:
        import jax

        from ..parallel.mesh import init_distributed

        init_distributed()
        # a flat mesh consumes devices in order, so the last host owns a
        # worker only if the count reaches into its device block
        min_workers = ((jax.process_count() - 1)
                       * jax.local_device_count() + 1)
        if a.slices <= 1 and a.num_workers < min_workers:
            raise SystemExit(
                f"num_workers ({a.num_workers}) leaves some of the "
                f"{jax.process_count()} hosts with no worker; need >= "
                f"{min_workers} (or use --slices for a hierarchical mesh)")
    if a.slices > 1:
        if a.num_workers % a.slices:
            raise SystemExit(
                f"num_workers ({a.num_workers}) must be divisible by "
                f"--slices ({a.slices})")
        from ..parallel.mesh import make_hierarchical_mesh

        return make_hierarchical_mesh(a.slices, a.num_workers // a.slices)
    return None
