"""Shared distributed-flags plumbing for the app entry points.

One place for the --multihost/--slices/--dcn-interval surface so every app
validates the same way (the reference apps share their driver loop shape the
same way, CifarApp.scala vs ImageNetApp.scala).
"""

from __future__ import annotations

from typing import Optional


def add_distributed_args(p, *, batch_default: int,
                         tau_default: int) -> None:
    p.add_argument("--multihost", action="store_true",
                   help="jax.distributed bring-up (call on every TPU-VM "
                        "worker; auto-detects on Cloud TPU)")
    p.add_argument("--slices", type=int, default=1,
                   help=">1 uses a (dcn, workers) hierarchical mesh")
    p.add_argument("--dcn-interval", type=int, default=1,
                   help="cross-slice average every k-th round")
    p.add_argument("--batch", type=int, default=batch_default)
    p.add_argument("--tau", type=int, default=tau_default,
                   help="local SGD steps between weight averages")


def mesh_from_args(a) -> Optional[object]:
    """Validate the flag combination and build the mesh (None = flat
    default).  Fail fast at parse time, not deep inside the solver."""
    if a.dcn_interval != 1 and a.slices <= 1:
        raise SystemExit("--dcn-interval needs --slices > 1")
    if a.multihost:
        import jax

        from ..parallel.mesh import init_distributed

        init_distributed()
        # a flat mesh consumes devices in order, so the last host owns a
        # worker only if the count reaches into its device block
        min_workers = ((jax.process_count() - 1)
                       * jax.local_device_count() + 1)
        if a.slices <= 1 and a.num_workers < min_workers:
            raise SystemExit(
                f"num_workers ({a.num_workers}) leaves some of the "
                f"{jax.process_count()} hosts with no worker; need >= "
                f"{min_workers} (or use --slices for a hierarchical mesh)")
    if a.slices > 1:
        if a.num_workers % a.slices:
            raise SystemExit(
                f"num_workers ({a.num_workers}) must be divisible by "
                f"--slices ({a.slices})")
        from ..parallel.mesh import make_hierarchical_mesh

        return make_hierarchical_mesh(a.slices, a.num_workers // a.slices)
    return None
