"""DB-fed app variants (reference: src/main/scala/apps/CifarDBApp.scala,
ImageNetCreateDBApp.scala, ImageNetRunDBApp.scala): one app materializes the
preprocessed dataset into a store, the other trains from it — decoupling
ingest from training exactly like the reference's LevelDB path.

    python -m sparknet_tpu.apps.db_apps create --cifar DIR --out STORE
    python -m sparknet_tpu.apps.db_apps create --shards DIR --labels F --out STORE
    python -m sparknet_tpu.apps.db_apps run N --store STORE [--model quick]
        [--warm-start W.npz]
"""

from __future__ import annotations

import argparse
from typing import Optional

import numpy as np

from ..data.cifar import CifarLoader
from ..data.store import ArrayStoreCursor, ArrayStoreWriter
from ..utils.logging import PhaseLogger
from . import cifar_app


def create_from_cifar(cifar_dir: str, out: str, txn_size: int = 1000) -> int:
    """(reference: CifarDBApp's CreateDB pass / preprocessing/CreateDB.scala)"""
    loader = CifarLoader(cifar_dir)
    w = ArrayStoreWriter(out, txn_size=txn_size)
    for img, label in zip(loader.train_images, loader.train_labels):
        w.put(img, int(label))
    w.close()
    return len(loader.train_labels)


def create_from_tars(shards_dir: str, label_file: str, out: str,
                     height: int = 256, width: int = 256,
                     txn_size: int = 1000) -> int:
    """(reference: ImageNetCreateDBApp.scala — tar shards -> resize -> DB)"""
    from ..data.imagenet import ImageNetLoader
    from ..data.scale_convert import convert_stream

    loader = ImageNetLoader(shards_dir)
    labels = loader.load_label_map(label_file)
    w = ArrayStoreWriter(out, txn_size=txn_size)
    count = 0
    for path in loader.get_file_paths():
        for arr, label in convert_stream(loader.read_tar(path, labels),
                                         height, width):
            w.put(arr, label)
            count += 1
    w.close()
    return count


def run_from_store(num_workers: int, store: str, *, model: str = "quick",
                   rounds: int = 50, batch_size: int = 100, tau: int = 10,
                   warm_start: Optional[str] = None, mesh=None,
                   log_path: Optional[str] = None,
                   native_feed: bool = False) -> float:
    """Train from a store (reference: ImageNetRunDBApp.scala — DB-fed
    training with optional .caffemodel warm start at :75).  native_feed
    streams each worker's partition through the C++ prefetcher (labels
    must fit one byte); either way round N+1 is staged while round N
    computes (set_prefetch)."""
    log = PhaseLogger(log_path)
    solver = cifar_app.build_solver(model, num_workers, tau,
                                    batch_size=batch_size, mesh=mesh)
    if warm_start:
        z = np.load(warm_start)
        params0 = {k: z[k] for k in z.files}
        weights = {}
        import jax

        flat = {k: jax.numpy.asarray(v) for k, v in params0.items()}
        weights = solver.net.get_weights(flat)
        solver.set_weights(weights)
        log("warm-started from " + warm_start)
    tmp_dir = None
    if native_feed:
        import tempfile

        from ..data.native_loader import (NativeRecordLoader,
                                          export_shard_record_files)

        cur = ArrayStoreCursor(store)
        c, h, wd = cur.datum_shape
        tmp_dir = tempfile.mkdtemp(prefix="sparknet_dbshards_")
        # O(one record) streaming export — the store may be ImageNet-scale
        paths = export_shard_record_files(
            (cur.next() for _ in range(len(cur))), num_workers, tmp_dir)
        feeds = [NativeRecordLoader([p], channels=c, height=h, width=wd,
                                    batch=batch_size, seed=1 + w)
                 for w, p in enumerate(paths)]
        log("native prefetcher feeds enabled")
    else:
        cursors = [ArrayStoreCursor(store) for _ in range(num_workers)]
        # stagger cursors so workers see different data (partition analogue)
        for w, c in enumerate(cursors):
            skip = (len(c) // num_workers) * w
            for _ in range(skip):
                c.next()
        feeds = []
        for c in cursors:
            it = c.batches(batch_size)

            def feed(it=it):
                b = next(it)
                return {"data": b["data"].astype(np.float32),
                        "label": b["label"]}

            feeds.append(feed)
    solver.set_train_data(feeds)
    solver.set_prefetch(True)  # stream feeds: stage round N+1 during N
    loss = 0.0
    try:
        for r in range(rounds):
            loss = solver.run_round(prefetch_next=r < rounds - 1)
            log(f"round lr = "
                f"{solver.current_lr():.8g}", i=r)
            log(f"round loss = {loss}", i=r)
    finally:
        log.close()
        for f in feeds:
            if hasattr(f, "close"):
                f.close()
        if tmp_dir:
            import shutil

            shutil.rmtree(tmp_dir, ignore_errors=True)
    return loss


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="verb", required=True)
    c = sub.add_parser("create")
    c.add_argument("--cifar")
    c.add_argument("--shards")
    c.add_argument("--labels")
    c.add_argument("--out", required=True)
    r = sub.add_parser("run")
    r.add_argument("num_workers", type=int)
    r.add_argument("--store", required=True)
    r.add_argument("--model", default="quick")
    r.add_argument("--rounds", type=int, default=50)
    r.add_argument("--warm-start")
    r.add_argument("--native-feed", action="store_true",
                   help="stream partitions through the C++ prefetcher")
    a = p.parse_args()
    if a.verb == "create":
        if a.cifar:
            n = create_from_cifar(a.cifar, a.out)
        else:
            n = create_from_tars(a.shards, a.labels, a.out)
        print(f"wrote {n} records to {a.out}")
    else:
        loss = run_from_store(a.num_workers, a.store, model=a.model,
                              rounds=a.rounds, warm_start=a.warm_start,
                              native_feed=a.native_feed)
        print(f"final loss {loss}")


if __name__ == "__main__":
    main()
