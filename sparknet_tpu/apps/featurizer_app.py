"""FeaturizerApp: forward-only feature extraction reading an intermediate
blob (reference: src/main/scala/apps/FeaturizerApp.scala:88-103 — forwards
minibatches through the net and reads blob `ip1` via getData).

Since the compound-serving PR the app rides the serving engine's
`capture_blob` execution path (serving/engine.py ModelRunner), so offline
featurization and a served `--model_type featurize` lane share ONE jitted
forward — same bucket machinery, same blob readback, bitwise-identical
features.  The historical tail-drop bug (the pre-rebase loop computed
``n = (len(data) // batch_size) * batch_size`` and silently discarded the
remainder rows) is fixed here: the final short batch is zero-padded to
the bucket and the output sliced back to the true row count.

    python -m sparknet_tpu.apps.featurizer_app --model NET.prototxt
        [--weights W.npz] --data D.npz --blob ip1 --out features.npz
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional

import numpy as np

from ..proto import caffe_pb


def featurize(net_prototxt: str, data: np.ndarray, blob: str = "ip1", *,
              weights_path: Optional[str] = None, batch_size: int = 100,
              labels: Optional[np.ndarray] = None,
              extra_shapes: Optional[Dict] = None) -> np.ndarray:
    """Forward batches, collect `blob` activations
    (reference: FeaturizerApp.scala:88-103; blob readback = the bridge's
    getData path, Net.scala:174-192).

    Every row of `data` produces a feature row — a trailing partial
    batch is padded to `batch_size` for the bucketed forward and the
    padding rows sliced off the result.  `labels` is accepted for
    call-site compatibility but does not influence intermediate
    activations (the engine zero-fills declared aux blobs, exactly as
    the classify path does); capture a label-independent blob.
    """
    from ..serving.engine import ModelRunner

    net_param = caffe_pb.load_net_prototxt(net_prototxt)
    net_param = caffe_pb.replace_data_layers(
        net_param, batch_size, batch_size, *data.shape[1:])
    runner = ModelRunner(net_param, weights=weights_path,
                         buckets=[batch_size], max_batch=batch_size,
                         capture_blob=blob, data_shapes=extra_shapes)
    data = np.asarray(data, dtype=np.float32)
    out: List[np.ndarray] = []
    for i in range(0, len(data), batch_size):
        chunk = data[i:i + batch_size]
        n_real = len(chunk)
        if n_real < batch_size:
            pad = np.zeros((batch_size - n_real,) + chunk.shape[1:],
                           np.float32)
            chunk = np.concatenate([chunk, pad])
        out.append(runner.forward_padded(chunk)[:n_real])
    flat = (np.concatenate(out) if out
            else np.zeros((0, runner.n_outputs), np.float32))
    # the engine flattens captured activations to (batch, -1) so the
    # serving response contract holds; restore the blob's true per-row
    # shape for offline callers (conv captures stay (N, C, H, W))
    feat_shape = tuple(runner.net.blob_shapes[blob][1:])
    return flat.reshape((len(data),) + feat_shape)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", required=True)
    p.add_argument("--weights")
    p.add_argument("--data", required=True)
    p.add_argument("--blob", default="ip1")
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--out", default="features.npz")
    a = p.parse_args()
    z = np.load(a.data)
    feats = featurize(a.model, z["data"], a.blob, weights_path=a.weights,
                      batch_size=a.batch,
                      labels=z["label"] if "label" in z.files else None)
    np.savez(a.out, features=feats)
    print(f"wrote {feats.shape} features to {a.out}")


if __name__ == "__main__":
    main()
