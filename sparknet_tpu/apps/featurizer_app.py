"""FeaturizerApp: forward-only feature extraction reading an intermediate
blob (reference: src/main/scala/apps/FeaturizerApp.scala:88-103 — forwards
minibatches through the net and reads blob `ip1` via getData).

    python -m sparknet_tpu.apps.featurizer_app --model NET.prototxt
        [--weights W.npz] --data D.npz --blob ip1 --out features.npz
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional

import numpy as np

from ..core.net import Net
from ..proto import caffe_pb


def featurize(net_prototxt: str, data: np.ndarray, blob: str = "ip1", *,
              weights_path: Optional[str] = None, batch_size: int = 100,
              labels: Optional[np.ndarray] = None,
              extra_shapes: Optional[Dict] = None) -> np.ndarray:
    """Forward batches, collect `blob` activations
    (reference: FeaturizerApp.scala:88-103; blob readback = the bridge's
    getData path, Net.scala:174-192)."""
    import jax
    import jax.numpy as jnp

    net_param = caffe_pb.load_net_prototxt(net_prototxt)
    net_param = caffe_pb.replace_data_layers(
        net_param, batch_size, batch_size, *data.shape[1:])
    net = Net(net_param, "TEST", data_shapes=extra_shapes)
    params = net.init_params(0)
    if weights_path:
        z = np.load(weights_path)
        params = {k: jnp.asarray(z[k]) for k in z.files}
    if blob not in net.blob_shapes:
        raise ValueError(f"blob {blob!r} not in net; have "
                         f"{sorted(net.blob_shapes)}")

    @jax.jit
    def fwd(p, x, y):
        blobs, _ = net.apply(p, {"data": x, "label": y}, train=False)
        return blobs[blob]

    out: List[np.ndarray] = []
    n = (len(data) // batch_size) * batch_size
    if labels is None:
        labels = np.zeros(len(data), dtype=np.int32)
    for i in range(0, n, batch_size):
        out.append(np.asarray(fwd(params,
                                  jnp.asarray(data[i:i + batch_size],
                                              dtype=jnp.float32),
                                  jnp.asarray(labels[i:i + batch_size]))))
    return np.concatenate(out) if out else np.zeros((0,))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", required=True)
    p.add_argument("--weights")
    p.add_argument("--data", required=True)
    p.add_argument("--blob", default="ip1")
    p.add_argument("--batch", type=int, default=100)
    p.add_argument("--out", default="features.npz")
    a = p.parse_args()
    z = np.load(a.data)
    feats = featurize(a.model, z["data"], a.blob, weights_path=a.weights,
                      batch_size=a.batch,
                      labels=z["label"] if "label" in z.files else None)
    np.savez(a.out, features=feats)
    print(f"wrote {feats.shape} features to {a.out}")


if __name__ == "__main__":
    main()
