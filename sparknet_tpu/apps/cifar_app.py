"""CifarApp: distributed CIFAR-10 training — the canonical entry point
(reference: src/main/scala/apps/CifarApp.scala).

Flow parity (CifarApp.scala:25-136): load CIFAR binaries -> partition across
N workers -> per-round windowed minibatch sampling (τ=10) -> τ local SGD
steps per worker -> weight average -> test every 10 rounds, logging accuracy
with elapsed seconds.  The Spark broadcast/collect machinery is replaced by
the one-program mesh round (parallel/dist.py).

Usage:
    python -m sparknet_tpu.apps.cifar_app NUM_WORKERS [--data DIR]
        [--model quick|full] [--rounds N] [--synthetic]
"""

from __future__ import annotations

import argparse
import os
import time
from typing import List, Optional, Tuple

import numpy as np

from ..data import partition as part
from ..data.cifar import CifarLoader
from ..data.sampler import MinibatchSampler
from ..parallel.dist import DistributedSolver
from ..proto import caffe_pb
from ..utils.logging import PhaseLogger

# (reference: CifarApp.scala:15-22)
TRAIN_BATCH_SIZE = 100
TEST_BATCH_SIZE = 100
CHANNELS, HEIGHT, WIDTH = 3, 32, 32
SYNC_INTERVAL = 10          # τ (CifarApp.scala:119)
TEST_EVERY_ROUNDS = 10      # (CifarApp.scala:101)

REFERENCE_PROTO_DIR = "/root/reference/caffe/examples/cifar10"


def synthetic_cifar(n_train=5000, n_test=1000, seed=0):
    """Learnable stand-in when the real dataset is unavailable (zero-egress
    environments): class = dominant color channel pattern + noise."""
    rng = np.random.RandomState(seed)

    def gen(n):
        labels = rng.randint(0, 10, size=n).astype(np.int32)
        base = rng.randint(0, 120, size=(n, 3, 32, 32))
        # class-dependent signal: bright block whose position/channel encodes
        # the label
        for i in range(n):
            c, r = labels[i] % 3, labels[i] // 3
            base[i, c, 8 * r:8 * r + 8, :] += 120
        return np.clip(base, 0, 255).astype(np.uint8), labels

    tr = gen(n_train)
    te = gen(n_test)
    return tr[0], tr[1], te[0], te[1]


def load_data(args) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray,
                             np.ndarray]:
    if args.synthetic or not os.path.isdir(args.data):
        xtr, ytr, xte, yte = synthetic_cifar()
    else:
        loader = CifarLoader(args.data)
        xtr, ytr = loader.train_images, loader.train_labels
        xte, yte = loader.test_images, loader.test_labels
    mean = xtr.astype(np.float64).mean(axis=0).astype(np.float32)
    return xtr, ytr, xte, yte, mean


def build_solver(model: str, n_workers: int, tau: int, mesh=None,
                 proto_dir: str = REFERENCE_PROTO_DIR,
                 batch_size: int = TRAIN_BATCH_SIZE,
                 dcn_interval: int = 1,
                 scan_unroll=1, mode: str = "average",
                 sync_history: str = "local") -> DistributedSolver:
    """ProtoLoader flow (CifarApp.scala:81-89): net prototxt ->
    replaceDataLayers -> solver-with-inline-net -> instantiate.
    mode="sync" selects per-step gradient pmean (the P2PSync analogue)
    instead of τ-averaging; sync_history averages/resets the momentum
    slots at each weight average.  The default "local" is the
    reference's WorkerStore behavior and right for this app's τ=10/50
    operating points; pass "average" when running τ ≲ 10 (measured 8w
    τ=1: 0.634 averaged vs 0.445 local — dist.py docstring /
    DISTACC.md)."""
    net = caffe_pb.load_net_prototxt(
        os.path.join(proto_dir, f"cifar10_{model}_train_test.prototxt"))
    net = caffe_pb.replace_data_layers(net, batch_size, batch_size,
                                       CHANNELS, HEIGHT, WIDTH)
    sp = caffe_pb.load_solver_prototxt_with_net(
        os.path.join(proto_dir, f"cifar10_{model}_solver.prototxt"), net)
    return DistributedSolver(sp, n_workers=n_workers, tau=tau, mesh=mesh,
                             dcn_interval=dcn_interval, mode=mode,
                             scan_unroll=scan_unroll,
                             sync_history=sync_history)


class WorkerFeed:
    """Per-round windowed sampling over this worker's shard
    (CifarApp.scala:120-130: a fresh MinibatchSampler per round)."""

    def __init__(self, images, labels, mean, batch_size, tau, seed):
        self.batches = part.make_minibatches(images, labels, batch_size)
        if not self.batches:
            raise ValueError(
                f"worker shard of {len(labels)} examples yields no full "
                f"batch of {batch_size}; decrease batch_size or workers")
        self.mean = mean
        self.tau = tau
        self.rng = np.random.RandomState(seed)
        self.sampler: Optional[MinibatchSampler] = None
        self._served = 0
        self._window = 0

    def fast_forward(self, n_rounds: int, pulls_per_round: int) -> None:
        """Advance the seed stream past `n_rounds` completed rounds of
        `pulls_per_round` __call__s each, for bit-exact resume from a
        snapshot.  Kept HERE because it must mirror this class's draw
        pattern: one randint in new_round plus one per mid-round window
        reopen in __call__ — i.e. ceil(pulls/window) per round."""
        window = min(self.tau, len(self.batches))
        draws = -(-pulls_per_round // window)
        for _ in range(n_rounds * draws):
            self.rng.randint(0, 2 ** 31)

    def new_round(self):
        # a shard can hold fewer batches than τ (tiny/synthetic datasets on
        # many workers): the window clamps to the shard and __call__ opens a
        # fresh window when it runs dry mid-round
        self._window = min(self.tau, len(self.batches))
        self.sampler = MinibatchSampler(
            iter(self.batches), len(self.batches), self._window,
            seed=int(self.rng.randint(0, 2 ** 31)))
        self._served = 0

    def __call__(self):
        if self.sampler is None or self._served >= self._window:
            self.new_round()
        self._served += 1
        b = self.sampler.next_batch()
        return {"data": b["data"].astype(np.float32) - self.mean,
                "label": b["label"]}


def run(num_workers: int, *, model: str = "quick", rounds: int = 100,
        data_dir: str = "", synthetic: bool = False,
        log_path: Optional[str] = None, mesh=None,
        target_accuracy: Optional[float] = None,
        batch_size: int = TRAIN_BATCH_SIZE, tau: int = SYNC_INTERVAL,
        dcn_interval: int = 1, snapshot_every_rounds: int = 0,
        snapshot_prefix: str = "", resume: str = "",
        native_feed: Optional[bool] = None) -> float:
    """native_feed: stream worker shards through the C++ prefetcher
    (reader+transform threads + one-round-ahead staging) instead of the
    Python windowed sampler.  Default (None): on for real CIFAR data — the
    hot path the reference runs through its prefetching data layer
    (base_data_layer.cpp:70-98) — off for synthetic, which keeps the
    MinibatchSampler flow-parity semantics AND the exact kill-and-resume
    replay (the native reader threads make batch order scheduling-
    dependent, so resume with native_feed continues the stream but is not
    bit-exact)."""
    args = argparse.Namespace(data=data_dir, synthetic=synthetic)
    log = PhaseLogger(log_path or
                      f"/tmp/training_log_{int(time.time())}.txt")
    log(f"rounds = {rounds}, workers = {num_workers}, model = {model}")

    xtr, ytr, xte, yte, mean = load_data(args)
    log("loaded data")
    shards = part.partition(xtr, ytr, num_workers)
    solver = build_solver(model, num_workers, tau, mesh=mesh,
                          batch_size=batch_size, dcn_interval=dcn_interval)
    log("built solver")

    if native_feed is None:
        native_feed = not (synthetic or not os.path.isdir(data_dir))
    shard_dir = None
    if native_feed:
        import tempfile

        from ..data.native_loader import native_feeds_from_arrays

        shard_dir = tempfile.mkdtemp(prefix="sparknet_shards_")
        feeds = native_feeds_from_arrays(shards, mean=mean,
                                         batch=batch_size, seed0=1,
                                         out_dir=shard_dir)
        solver.set_train_data(feeds)
        solver.set_prefetch(True)  # stream feeds: stage N+1 during N
        log("native prefetcher feeds enabled")
    else:
        feeds = [WorkerFeed(x, y, mean, batch_size, tau, seed=w)
                 for w, (x, y) in enumerate(shards)]
        solver.set_train_data(feeds)

    test_batches = part.make_minibatches(xte, yte, batch_size)
    num_test = len(test_batches)

    def test_source():
        test_source.i = (getattr(test_source, "i", -1) + 1) % num_test
        x, y = test_batches[test_source.i]
        return {"data": x.astype(np.float32) - mean, "label": y}

    solver.set_test_data(test_source, num_test)

    from .common import (check_snapshot_args, maybe_snapshot_round,
                         resume_and_replay)
    check_snapshot_args(snapshot_every_rounds, snapshot_prefix)
    start_round = 0
    if resume:
        start_round = resume_and_replay(
            solver, resume, feeds, log,
            per_round=(None if native_feed
                       else (lambda f: f.new_round())))

    accuracy = 0.0
    try:
        for r in range(start_round, rounds):
            if not native_feed:
                for f in feeds:
                    f.new_round()
            if r % TEST_EVERY_ROUNDS == 0:
                log("starting testing", i=r)
                scores = solver.test()
                accuracy = scores.get("accuracy", scores.get("acc", 0.0))
                if "loss" in scores:  # test-net loss, for plot types 2/3
                    log(f"test loss = {scores['loss']}", i=r)
                log(f"%-age of test set correct: {accuracy}", i=r)
                if target_accuracy and accuracy >= target_accuracy:
                    log(f"target accuracy {target_accuracy} reached", i=r)
                    return accuracy
            log("starting training", i=r)
            loss = solver.run_round(prefetch_next=r < rounds - 1)
            log(f"round lr = "
                f"{solver.current_lr():.8g}", i=r)
            log(f"round loss = {loss}", i=r)
            maybe_snapshot_round(solver, log, r, snapshot_every_rounds,
                                 snapshot_prefix)
        scores = solver.test()
        accuracy = scores.get("accuracy", scores.get("acc", 0.0))
        if "loss" in scores:
            log(f"test loss = {scores['loss']}")
        log(f"final %-age of test set correct: {accuracy}")
        return accuracy
    finally:
        log.close()
        if native_feed:
            for f in feeds:
                if hasattr(f, "close"):
                    f.close()
            if shard_dir:
                import shutil

                shutil.rmtree(shard_dir, ignore_errors=True)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("num_workers", type=int)
    p.add_argument("--data", default="/root/data/cifar10")
    p.add_argument("--model", default="quick", choices=["quick", "full"])
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--native-feed", dest="native_feed", action="store_true",
                   default=None,
                   help="stream shards through the C++ prefetcher "
                        "(default: on for real data)")
    p.add_argument("--no-native-feed", dest="native_feed",
                   action="store_false")
    from ..utils.compile_cache import (apply_platform_env,
                                      maybe_enable_compile_cache)
    from .common import (add_distributed_args, add_snapshot_args,
                         mesh_from_args)

    apply_platform_env()
    maybe_enable_compile_cache()
    add_distributed_args(p, batch_default=TRAIN_BATCH_SIZE,
                         tau_default=SYNC_INTERVAL)
    add_snapshot_args(p)
    a = p.parse_args()
    mesh = mesh_from_args(a)
    run(a.num_workers, model=a.model, rounds=a.rounds, data_dir=a.data,
        synthetic=a.synthetic, mesh=mesh, dcn_interval=a.dcn_interval,
        batch_size=a.batch, tau=a.tau,
        snapshot_every_rounds=a.snapshot_every_rounds,
        snapshot_prefix=a.snapshot_prefix, resume=a.resume,
        native_feed=a.native_feed)


if __name__ == "__main__":
    main()
