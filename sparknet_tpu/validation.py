"""Numerical validation of the training engine against the reference's
update math, at float64, over long horizons.

"Caffe layer/solver semantics preserved" must be demonstrated, not
asserted: this module runs the framework's jitted Solver next to an
INDEPENDENT NumPy implementation of the reference's forward/backward/update
pipeline (the formulas in caffe/src/caffe/solvers/*.cpp and
softmax_loss_layer.cpp, re-derived here by hand — not a port of the
framework's own jax code) on an identical fixed data stream, and reports
per-iteration loss/parameter drift.  At float64 any semantic difference
(wrong momentum formulation, wrong LR schedule, wrong regularizer order)
shows up as super-rounding-level divergence within a few iterations.

The model is the smallest net that exercises the full pipeline —
InnerProduct + SoftmaxWithLoss — so the hand NumPy gradient is exact:
  logits = x_flat @ W.T + b                 (inner_product_layer.cpp:46-60)
  L = -mean(log softmax(logits)[label])     (softmax_loss_layer.cpp:74-80)
  dlogits = (softmax - onehot) / N          (softmax_loss_layer.cpp:105-120)
  dW = dlogits.T @ x_flat ; db = sum dlogits
then weight decay (sgd_solver.cpp:119-160), LR policy (sgd_solver.cpp:27-64)
and the per-solver update (solvers/*.cpp) are applied in the reference's
order.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

SOLVER_HYPERS: Dict[str, Dict[str, float]] = {
    # per-type hyperparameters in the reference's customary ranges
    "SGD": dict(base_lr=0.05, momentum=0.9),
    "Nesterov": dict(base_lr=0.05, momentum=0.9),
    "AdaGrad": dict(base_lr=0.05, momentum=0.0, delta=1e-8),
    "RMSProp": dict(base_lr=0.01, momentum=0.0, rms_decay=0.98, delta=1e-8),
    "AdaDelta": dict(base_lr=1.0, momentum=0.95, delta=1e-6),
    "Adam": dict(base_lr=0.01, momentum=0.9, momentum2=0.999, delta=1e-8),
}


def _lr(base_lr: float, policy: str, it: int, *, gamma: float = 0.0001,
        power: float = 0.75, stepsize: int = 100) -> float:
    """LR policies, re-derived from sgd_solver.cpp:27-64."""
    if policy == "fixed":
        return base_lr
    if policy == "inv":
        return base_lr * (1.0 + gamma * it) ** (-power)
    if policy == "step":
        return base_lr * (gamma ** (it // stepsize))
    raise ValueError(policy)


def _softmax_loss_bwd(logits: np.ndarray, y: np.ndarray
                      ) -> Tuple[float, np.ndarray]:
    """Shared softmax + NLL forward/backward
    (softmax_loss_layer.cpp:74-120): returns (mean loss, dlogits)."""
    n = logits.shape[0]
    z = logits - logits.max(axis=1, keepdims=True)
    e = np.exp(z)
    p = e / e.sum(axis=1, keepdims=True)
    loss = float(-np.mean(np.log(np.maximum(p[np.arange(n), y], 1e-300))))
    d = p.copy()
    d[np.arange(n), y] -= 1.0
    d /= n
    return loss, d


class NumpyReferenceSolver:
    """Hand implementation of the reference training iteration at float64."""

    def __init__(self, solver_type: str, w: np.ndarray, b: np.ndarray, *,
                 lr_policy: str = "inv", weight_decay: float = 5e-4,
                 clip: float = 0.0) -> None:
        self.type = solver_type
        self.hy = SOLVER_HYPERS[solver_type]
        self.lr_policy = lr_policy
        self.weight_decay = weight_decay
        self.clip = clip
        self.w = w.astype(np.float64).copy()
        self.b = b.astype(np.float64).copy()
        n_slots = 2 if solver_type in ("AdaDelta", "Adam") else 1
        self.hist = {name: [np.zeros_like(p) for _ in range(n_slots)]
                     for name, p in (("w", self.w), ("b", self.b))}
        self.it = 0

    # ---- forward/backward (inner_product + softmax loss, re-derived)
    def _fwd_bwd(self, x: np.ndarray, y: np.ndarray
                 ) -> Tuple[float, np.ndarray, np.ndarray]:
        n = x.shape[0]
        xf = x.reshape(n, -1).astype(np.float64)
        loss, d = _softmax_loss_bwd(xf @ self.w.T + self.b, y)
        return loss, d.T @ xf, d.sum(axis=0)

    def _update_one(self, name: str, p: np.ndarray, g: np.ndarray,
                    lr: float) -> np.ndarray:
        hy = self.hy
        h = self.hist[name]
        t = self.type
        if t == "SGD":
            v = hy["momentum"] * h[0] + lr * g
            h[0] = v
            return p - v
        if t == "Nesterov":
            v_prev = h[0]
            v = hy["momentum"] * v_prev + lr * g
            h[0] = v
            return p - ((1.0 + hy["momentum"]) * v
                        - hy["momentum"] * v_prev)
        if t == "AdaGrad":
            h[0] = h[0] + g * g
            return p - lr * g / (np.sqrt(h[0]) + hy["delta"])
        if t == "RMSProp":
            h[0] = hy["rms_decay"] * h[0] + (1.0 - hy["rms_decay"]) * g * g
            return p - lr * g / (np.sqrt(h[0]) + hy["delta"])
        if t == "AdaDelta":
            mom, delta = hy["momentum"], hy["delta"]
            h[0] = mom * h[0] + (1.0 - mom) * g * g
            upd = g * np.sqrt((delta + h[1]) / (delta + h[0]))
            h[1] = mom * h[1] + (1.0 - mom) * upd * upd
            return p - lr * upd
        if t == "Adam":
            m1, m2 = hy["momentum"], hy["momentum2"]
            step = self.it + 1
            h[0] = m1 * h[0] + (1.0 - m1) * g
            h[1] = m2 * h[1] + (1.0 - m2) * g * g
            corr = np.sqrt(1.0 - m2 ** step) / (1.0 - m1 ** step)
            return p - lr * corr * h[0] / (np.sqrt(h[1]) + hy["delta"])
        raise ValueError(t)

    def step(self, x: np.ndarray, y: np.ndarray) -> float:
        loss, gw, gb = self._fwd_bwd(x, y)
        if self.clip > 0:
            l2 = np.sqrt((gw * gw).sum() + (gb * gb).sum())
            if l2 > self.clip:
                gw, gb = gw * self.clip / l2, gb * self.clip / l2
        # L2 regularization in the reference's order: after clip, before the
        # solver update (sgd_solver.cpp:102-117 ApplyUpdate)
        gw = gw + self.weight_decay * self.w
        gb = gb + self.weight_decay * self.b
        lr = _lr(self.hy["base_lr"], self.lr_policy, self.it)
        self.w = self._update_one("w", self.w, gw, lr)
        self.b = self._update_one("b", self.b, gb, lr)
        self.it += 1
        return loss


def make_stream(iters: int, batch: int = 8, dim: Tuple[int, ...] = (1, 4, 4),
                classes: int = 5, seed: int = 0
                ) -> List[Tuple[np.ndarray, np.ndarray]]:
    rng = np.random.RandomState(seed)
    return [(rng.rand(batch, *dim).astype(np.float64),
             rng.randint(0, classes, size=batch).astype(np.int32))
            for _ in range(iters)]


def trajectory_compare(solver_type: str, iters: int = 500, *,
                       lr_policy: str = "inv", weight_decay: float = 5e-4,
                       clip: float = 0.0, seed: int = 0) -> Dict[str, float]:
    """Run the framework Solver and the NumPy reference side by side at
    float64 on one fixed stream.  Returns drift statistics."""
    import jax

    from .utils.compile_cache import apply_platform_env

    # honor JAX_PLATFORMS=cpu even under a jax-preimporting sitecustomize:
    # TPU backends silently demote f64 to f32, which would turn this
    # double-precision harness into a no-op comparison
    apply_platform_env()
    if jax.default_backend() not in ("cpu",):
        raise RuntimeError(
            "the float64 trajectory harness needs the CPU backend "
            "(set JAX_PLATFORMS=cpu); TPU demotes float64 silently")
    jax.config.update("jax_enable_x64", True)
    try:
        return _trajectory_compare_x64(solver_type, iters,
                                       lr_policy=lr_policy,
                                       weight_decay=weight_decay, clip=clip,
                                       seed=seed)
    finally:
        jax.config.update("jax_enable_x64", False)


def _trajectory_compare_x64(solver_type: str, iters: int, *, lr_policy: str,
                            weight_decay: float, clip: float,
                            seed: int) -> Dict[str, float]:
    import jax.numpy as jnp

    from .proto import caffe_pb
    from .proto.textformat import parse
    from .solver.solver import Solver

    hy = SOLVER_HYPERS[solver_type]
    lines = [f"base_lr: {hy['base_lr']}", f'lr_policy: "{lr_policy}"',
             'gamma: 0.0001', 'power: 0.75', 'stepsize: 100',
             f"weight_decay: {weight_decay}", f'type: "{solver_type}"',
             'random_seed: 11']
    if clip > 0:
        lines.append(f"clip_gradients: {clip}")
    for key, field in (("momentum", "momentum"), ("delta", "delta"),
                       ("momentum2", "momentum2"),
                       ("rms_decay", "rms_decay")):
        if key in hy:
            lines.append(f"{field}: {hy[key]}")
    net_txt = """
name: "tiny"
layer { name: "data" type: "MemoryData" top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 4 width: 4 } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 5
    weight_filler { type: "gaussian" std: 0.3 }
    bias_filler { type: "constant" value: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label"
  top: "loss" }
"""
    sp = caffe_pb.SolverParameter(parse("\n".join(lines)))
    sp.msg.set("net_param", caffe_pb.parse_net_text(net_txt).msg)
    solver = Solver(sp)
    # promote the framework solver to float64 end to end
    solver.params = {k: jnp.asarray(np.asarray(v), jnp.float64)
                     for k, v in solver.params.items()}
    solver.state = {k: tuple(jnp.asarray(np.asarray(h), jnp.float64)
                             for h in v)
                    for k, v in solver.state.items()}

    wkey, bkey = "ip/0", "ip/1"  # blob 0 = weight, blob 1 = bias
    ref = NumpyReferenceSolver(solver_type,
                               np.asarray(solver.params[wkey]),
                               np.asarray(solver.params[bkey]),
                               lr_policy=lr_policy,
                               weight_decay=weight_decay, clip=clip)

    stream = make_stream(iters, seed=seed)
    idx = {"i": 0}

    def source():
        x, y = stream[idx["i"] % len(stream)]
        idx["i"] += 1
        return {"data": x, "label": y}

    solver.set_train_data(source)

    max_loss_diff = 0.0
    losses_fw: List[float] = []
    losses_ref: List[float] = []
    for i in range(iters):
        # step the framework one iteration (its pull consumes stream[i])
        solver.step(1)
        loss_fw = solver._loss_window[-1]
        x, y = stream[i]
        loss_ref = ref.step(x, y)
        losses_fw.append(loss_fw)
        losses_ref.append(loss_ref)
        max_loss_diff = max(max_loss_diff, abs(loss_fw - loss_ref))

    w_fw = np.asarray(solver.params[wkey])
    b_fw = np.asarray(solver.params[bkey])
    denom = max(np.abs(ref.w).max(), 1e-12)
    return dict(
        solver=solver_type,
        iters=iters,
        max_loss_abs_diff=max_loss_diff,
        final_loss_framework=losses_fw[-1],
        final_loss_reference=losses_ref[-1],
        max_w_rel_diff=float(np.abs(w_fw - ref.w).max() / denom),
        max_b_abs_diff=float(np.abs(b_fw - ref.b).max()),
    )


def run_all(iters: int = 500) -> List[Dict[str, float]]:
    return [trajectory_compare(t, iters) for t in SOLVER_HYPERS]



# ====================================================================== conv
# Conv-stack trajectory validation (VERDICT r2 item 5): hand-derived NumPy
# forward/backward for Convolution, Pooling (MAX+AVE, Caffe window
# clipping and tie rules), ReLU, LRN (both norm regions), and
# InnerProduct — an interpreter over the REFERENCE's own prototxt, so the
# verified topology is literally caffe/examples/cifar10/
# cifar10_{quick,full}_train_test.prototxt.  Formulas re-derived from
# conv_layer.cpp / im2col.cpp, pooling_layer.cpp:90-221,
# lrn_layer.cpp:118-242 (cross-channel) and its within-channel
# pool-of-squares composition, inner_product_layer.cpp:46-60.  NOT a port
# of the framework's jax code.


def _conv_out_dim(size: int, k: int, p: int, s: int) -> int:
    # conv_layer.cpp compute_output_shape: floor((H + 2p - k)/s) + 1
    return (size + 2 * p - k) // s + 1


def _pool_out_dim(size: int, k: int, p: int, s: int) -> int:
    # pooling_layer.cpp Reshape: ceil((H + 2p - k)/s) + 1, then drop a
    # window that would start in the padding
    out = -(-(size + 2 * p - k) // s) + 1
    if p > 0 and (out - 1) * s >= size + p:
        out -= 1
    return out


class _NpConv:
    """Convolution via im2col matmul — the reference's own formulation
    (conv_layer.cpp forward_cpu_gemm; im2col.cpp)."""

    def __init__(self, w_key, b_key, stride, pad):
        self.w_key, self.b_key = w_key, b_key
        self.s, self.p = stride, pad

    def _cols(self, x, k):
        n, c, h, w = x.shape
        oh = _conv_out_dim(h, k, self.p, self.s)
        ow = _conv_out_dim(w, k, self.p, self.s)
        xp = np.pad(x, ((0, 0), (0, 0), (self.p, self.p), (self.p, self.p)))
        cols = np.empty((n, c, k, k, oh, ow), dtype=np.float64)
        for ky in range(k):
            for kx in range(k):
                cols[:, :, ky, kx] = xp[:, :, ky:ky + oh * self.s:self.s,
                                        kx:kx + ow * self.s:self.s]
        return cols, oh, ow

    def fwd(self, x, params):
        w, b = params[self.w_key], params[self.b_key]
        o, c, k, _ = w.shape
        cols, oh, ow = self._cols(x, k)
        n = x.shape[0]
        flat = cols.reshape(n, c * k * k, oh * ow)
        out = np.einsum("of,nfs->nos", w.reshape(o, -1), flat)
        out += b[None, :, None]
        self._cache = (x.shape, flat, w.shape)
        return out.reshape(n, o, oh, ow)

    def bwd(self, dy, params, grads):
        (xshape, flat, wshape) = self._cache
        n, c, h, w_dim = xshape
        o, _, k, _ = wshape
        dyf = dy.reshape(n, o, -1)
        grads[self.w_key] = grads.get(self.w_key, 0) + np.einsum(
            "nos,nfs->of", dyf, flat).reshape(wshape)
        grads[self.b_key] = grads.get(self.b_key, 0) + dyf.sum(axis=(0, 2))
        dcols = np.einsum("of,nos->nfs", params[self.w_key].reshape(o, -1),
                          dyf)
        oh = _conv_out_dim(h, k, self.p, self.s)
        ow = _conv_out_dim(w_dim, k, self.p, self.s)
        dcols = dcols.reshape(n, c, k, k, oh, ow)
        dxp = np.zeros((n, c, h + 2 * self.p, w_dim + 2 * self.p))
        for ky in range(k):
            for kx in range(k):
                dxp[:, :, ky:ky + oh * self.s:self.s,
                    kx:kx + ow * self.s:self.s] += dcols[:, :, ky, kx]
        return dxp[:, :, self.p:self.p + h, self.p:self.p + w_dim]


class _NpPool:
    """MAX/AVE pooling with the reference's exact window rules
    (pooling_layer.cpp:90-221): MAX clips windows to the valid region and
    routes the gradient to the FIRST max in scan order (:163-168); AVE's
    divisor counts the window clipped to the PADDED region (:186-196)."""

    def __init__(self, mode, k, stride, pad):
        self.mode, self.k, self.s, self.p = mode, k, stride, pad

    def fwd(self, x, params):
        n, c, h, w = x.shape
        k, s, p = self.k, self.s, self.p
        oh, ow = _pool_out_dim(h, k, p, s), _pool_out_dim(w, k, p, s)
        out = np.empty((n, c, oh, ow))
        self._cache = (x.shape, [])
        for py in range(oh):
            for px in range(ow):
                hs, ws = py * s - p, px * s - p
                he, we = min(hs + k, h + p), min(ws + k, w + p)
                pool_size = (he - hs) * (we - ws)  # AVE divisor, pre-clip
                hs0, ws0 = max(hs, 0), max(ws, 0)
                he0, we0 = min(he, h), min(we, w)
                win = x[:, :, hs0:he0, ws0:we0]
                if self.mode == "MAX":
                    flat = win.reshape(n, c, -1)
                    idx = flat.argmax(axis=2)  # first max in scan order,
                    # matching the strict `>` scan of pooling_layer.cpp
                    out[:, :, py, px] = np.take_along_axis(
                        flat, idx[..., None], 2)[..., 0]
                    self._cache[1].append((hs0, ws0, he0 - hs0, we0 - ws0,
                                           idx))
                else:
                    out[:, :, py, px] = win.sum(axis=(2, 3)) / pool_size
                    self._cache[1].append((hs0, ws0, he0 - hs0, we0 - ws0,
                                           pool_size))
        return out

    def bwd(self, dy, params, grads):
        xshape, meta = self._cache
        n, c, h, w = xshape
        dx = np.zeros(xshape)
        oh, ow = dy.shape[2], dy.shape[3]
        i = 0
        for py in range(oh):
            for px in range(ow):
                if self.mode == "MAX":
                    hs0, ws0, wh, ww, idx = meta[i]
                    gy, gx_ = np.unravel_index(idx, (wh, ww))
                    nn, cc = np.meshgrid(np.arange(n), np.arange(c),
                                         indexing="ij")
                    np.add.at(dx, (nn, cc, hs0 + gy, ws0 + gx_),
                              dy[:, :, py, px])
                else:
                    hs0, ws0, wh, ww, pool_size = meta[i]
                    dx[:, :, hs0:hs0 + wh, ws0:ws0 + ww] += (
                        dy[:, :, py, px][:, :, None, None] / pool_size)
                i += 1
        return dx


class _NpReLU:
    def fwd(self, x, params):
        self._mask = x > 0
        return np.where(self._mask, x, 0.0)

    def bwd(self, dy, params, grads):
        return np.where(self._mask, dy, 0.0)


class _NpLRN:
    """LRN, both regions.  ACROSS_CHANNELS: scale_i = k + (alpha/n) *
    sum_{window} x_j^2, y = x * scale^-beta, backward per
    lrn_layer.cpp:118-242.  WITHIN_CHANNEL: the reference composes
    square -> AVE-pool(local_size, pad (n-1)/2) -> power(1 + alpha*s)^-beta
    -> product; forward/backward here follow that composition exactly."""

    def __init__(self, local_size, alpha, beta, k, region):
        self.n, self.alpha, self.beta, self.k = local_size, alpha, beta, k
        self.region = region
        if region == "WITHIN_CHANNEL":
            self.pool = _NpPool("AVE", local_size, 1, (local_size - 1) // 2)

    def fwd(self, x, params):
        if self.region == "ACROSS_CHANNELS":
            c = x.shape[1]
            half = (self.n - 1) // 2
            sq = x * x
            scale = np.full_like(x, self.k)
            for i in range(c):
                lo, hi = max(0, i - half), min(c, i - half + self.n)
                scale[:, i] += (self.alpha / self.n) * sq[:, lo:hi].sum(
                    axis=1)
            y = x * scale ** (-self.beta)
            self._cache = (x, y, scale)
            return y
        s = self.pool.fwd(x * x, params)
        f = (1.0 + self.alpha * s) ** (-self.beta)
        y = x * f
        self._cache = (x, s, f)
        return y

    def bwd(self, dy, params, grads):
        if self.region == "ACROSS_CHANNELS":
            x, y, scale = self._cache
            c = x.shape[1]
            half = (self.n - 1) // 2
            ratio = dy * y / scale
            acc = np.zeros_like(x)
            for i in range(c):
                lo, hi = max(0, i - half), min(c, i - half + self.n)
                acc[:, i] = ratio[:, lo:hi].sum(axis=1)
            return (dy * scale ** (-self.beta)
                    - (2.0 * self.alpha * self.beta / self.n) * x * acc)
        x, s, f = self._cache
        dx = dy * f
        df = dy * x
        ds = df * (-self.beta) * self.alpha * (
            1.0 + self.alpha * s) ** (-self.beta - 1.0)
        dsq = self.pool.bwd(ds, params, grads)
        return dx + 2.0 * x * dsq


class _NpIP:
    def __init__(self, w_key, b_key):
        self.w_key, self.b_key = w_key, b_key

    def fwd(self, x, params):
        n = x.shape[0]
        self._xf = x.reshape(n, -1)
        self._xshape = x.shape
        return self._xf @ params[self.w_key].T + params[self.b_key]

    def bwd(self, dy, params, grads):
        grads[self.w_key] = grads.get(self.w_key, 0) + dy.T @ self._xf
        grads[self.b_key] = grads.get(self.b_key, 0) + dy.sum(axis=0)
        return (dy @ params[self.w_key]).reshape(self._xshape)


class NumpyProtoNetSolver:
    """The reference's full training iteration for a conv-stack prototxt,
    at float64: forward/backward through the hand-derived layers above,
    then clip -> L2(decay_mult) -> lr_policy(lr_mult) -> solver update in
    the reference's order (sgd_solver.cpp:102-240).  Initial params are
    COPIED from the framework solver (dynamics are under test, not
    fillers)."""

    def __init__(self, net_param, params, *, solver_type="SGD",
                 base_lr=0.001, lr_policy="fixed", momentum=0.9,
                 weight_decay=0.004, lr_mults=None, decay_mults=None,
                 gamma=0.0001, power=0.75, stepsize=100, delta=None,
                 rms_decay=None, momentum2=None):
        self.type = solver_type
        self.hy = dict(SOLVER_HYPERS[solver_type])
        self.hy["base_lr"] = base_lr
        if momentum is not None and "momentum" in self.hy:
            self.hy["momentum"] = momentum
        # per-type hypers from the prototxt override the table defaults —
        # silently keeping a default for a field the prototxt sets would
        # misreport the divergence as a framework bug
        for k_, v_ in (("delta", delta), ("rms_decay", rms_decay),
                       ("momentum2", momentum2)):
            if v_ is not None and k_ in self.hy:
                self.hy[k_] = v_
        self.lr_policy = lr_policy
        self.lr_kwargs = dict(gamma=gamma, power=power, stepsize=stepsize)
        self.weight_decay = weight_decay
        self.params = {k: np.asarray(v, np.float64).copy()
                       for k, v in params.items()}
        self.lr_mults = dict(lr_mults or {})
        self.decay_mults = dict(decay_mults or {})
        n_slots = 2 if solver_type in ("AdaDelta", "Adam") else 1
        self.hist = {k: [np.zeros_like(p) for _ in range(n_slots)]
                     for k, p in self.params.items()}
        self.it = 0
        self.layers = []
        self._build(net_param)

    def _build(self, net_param):
        from .core.net import phase_matches
        from .proto.caffe_pb import NetState
        from .proto.textformat import Message

        state = NetState(Message())
        state.msg.set("phase", "TRAIN")
        pcount = {}
        for layer in net_param.layers:
            if not phase_matches(layer, state):
                continue
            t = str(layer.type)
            name = str(layer.name)
            wk, bk = f"{name}/0", f"{name}/1"
            if t == "Convolution":
                cp = layer.convolution_param
                (sh, sw), (ph, pw) = cp.stride, cp.pad
                assert sh == sw and ph == pw, "square geometry only here"
                if int(cp.group) != 1 or tuple(cp.dilation) != (1, 1):
                    raise ValueError(
                        f"{name}: grouped/dilated convolution is not "
                        f"modeled by _NpConv — extend it before trusting "
                        f"a drift report")
                self.layers.append(_NpConv(wk, bk, sh, ph))
            elif t == "Pooling":
                pp = layer.pooling_param
                (kh, kw), (sh, sw), (ph, pw) = (pp.kernel, pp.strides,
                                                pp.pads)
                assert kh == kw and sh == sw and ph == pw
                self.layers.append(_NpPool(str(pp.pool or "MAX"), kh, sh,
                                           ph))
            elif t == "ReLU":
                self.layers.append(_NpReLU())
            elif t == "LRN":
                lp = layer.lrn_param
                self.layers.append(_NpLRN(
                    int(lp.local_size or 5), float(lp.alpha or 1.0),
                    float(lp.beta or 0.75), float(lp.k or 1.0),
                    str(lp.norm_region or "ACROSS_CHANNELS")))
            elif t == "InnerProduct":
                self.layers.append(_NpIP(wk, bk))
            elif t in ("MemoryData", "Data", "SoftmaxWithLoss", "Accuracy"):
                continue
            else:
                raise ValueError(f"unsupported layer type {t}")

    def step(self, x, y):
        a = np.asarray(x, np.float64)
        for l in self.layers:
            a = l.fwd(a, self.params)
        loss, d = _softmax_loss_bwd(a, y)
        grads = {}
        for l in reversed(self.layers):
            d = l.bwd(d, self.params, grads)
        rate = _lr(self.hy["base_lr"], self.lr_policy, self.it,
                   **self.lr_kwargs)
        upd = NumpyReferenceSolver._update_one
        for k_name, p in self.params.items():
            g = grads[k_name]
            g = g + (self.weight_decay
                     * self.decay_mults.get(k_name, 1.0)) * p
            local_rate = rate * self.lr_mults.get(k_name, 1.0)
            shim = _UpdateShim(self.type, self.hy, self.hist[k_name],
                               self.it)
            self.params[k_name] = upd(shim, "p", p, g, local_rate)
        self.it += 1
        return loss


class _UpdateShim:
    """Adapter so NumpyReferenceSolver._update_one (the verified per-type
    update math) applies to an arbitrary param's history slots."""

    def __init__(self, type_, hy, hist_slots, it):
        self.type, self.hy, self.it = type_, hy, it
        self.hist = {"p": hist_slots}


def conv_trajectory_compare(model: str = "quick", iters: int = 60, *,
                            batch: int = 16, seed: int = 0,
                            proto_dir: str =
                            "/root/reference/caffe/examples/cifar10"
                            ) -> Dict[str, float]:
    """Float64 trajectory: framework Solver vs NumpyProtoNetSolver on the
    reference's own cifar10_{quick,full}_train_test.prototxt topology
    (conv/pool/LRN stack) under its solver hyperparameters."""
    import jax

    from .utils.compile_cache import apply_platform_env

    apply_platform_env()
    if jax.default_backend() not in ("cpu",):
        raise RuntimeError("float64 harness needs JAX_PLATFORMS=cpu")
    jax.config.update("jax_enable_x64", True)
    try:
        return _conv_trajectory_x64(model, iters, batch, seed, proto_dir)
    finally:
        jax.config.update("jax_enable_x64", False)


def _conv_trajectory_x64(model, iters, batch, seed, proto_dir):
    import os as _os

    import jax.numpy as jnp

    from .proto import caffe_pb
    from .solver.solver import Solver

    net_p = caffe_pb.load_net_prototxt(_os.path.join(
        proto_dir, f"cifar10_{model}_train_test.prototxt"))
    net_p = caffe_pb.replace_data_layers(net_p, batch, batch, 3, 32, 32)
    sp = caffe_pb.load_solver_prototxt_with_net(_os.path.join(
        proto_dir, f"cifar10_{model}_solver.prototxt"), net_p)
    sp.msg.set("random_seed", 7)
    solver = Solver(sp)
    solver.params = {k: jnp.asarray(np.asarray(v), jnp.float64)
                     for k, v in solver.params.items()}
    solver.state = {k: tuple(jnp.asarray(np.asarray(h), jnp.float64)
                             for h in v)
                    for k, v in solver.state.items()}

    if float(sp.clip_gradients) > 0:
        raise ValueError("clip_gradients is not modeled by "
                         "NumpyProtoNetSolver; extend step() first")
    ref = NumpyProtoNetSolver(
        net_p, {k: np.asarray(v) for k, v in solver.params.items()},
        solver_type=sp.resolved_type(), base_lr=float(sp.base_lr),
        lr_policy=str(sp.lr_policy), momentum=float(sp.momentum),
        weight_decay=float(sp.weight_decay),
        lr_mults=solver.net.lr_multipliers(),
        decay_mults=solver.net.decay_multipliers(),
        gamma=float(sp.gamma), power=float(sp.power),
        stepsize=int(sp.stepsize) or 100, delta=float(sp.delta),
        rms_decay=float(sp.rms_decay), momentum2=float(sp.momentum2))

    rng = np.random.RandomState(seed)
    stream = [(rng.rand(batch, 3, 32, 32) * 2.0 - 1.0,
               rng.randint(0, 10, size=batch).astype(np.int32))
              for _ in range(iters)]
    idx = {"i": 0}

    def source():
        x, y = stream[idx["i"] % len(stream)]
        idx["i"] += 1
        return {"data": x, "label": y}

    solver.set_train_data(source)

    max_loss_diff = 0.0
    loss_fw = loss_ref = 0.0
    for i in range(iters):
        solver.step(1)
        loss_fw = solver._loss_window[-1]
        x, y = stream[i]
        loss_ref = ref.step(x, y)
        max_loss_diff = max(max_loss_diff, abs(loss_fw - loss_ref))

    max_rel = 0.0
    worst = ""
    for k, p_ref in ref.params.items():
        p_fw = np.asarray(solver.params[k])
        denom = max(np.abs(p_ref).max(), 1e-12)
        rel = float(np.abs(p_fw - p_ref).max() / denom)
        if rel > max_rel:
            max_rel, worst = rel, k
    return dict(model=model, iters=iters, batch=batch,
                max_loss_abs_diff=max_loss_diff,
                final_loss_framework=loss_fw,
                final_loss_reference=loss_ref,
                max_param_rel_diff=max_rel, worst_param=worst)


if __name__ == "__main__":
    import json
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "conv":
        # conv-stack mode: python -m sparknet_tpu.validation conv [iters]
        #   [quick|full|both]
        iters = int(sys.argv[2]) if len(sys.argv) > 2 else 60
        which = sys.argv[3] if len(sys.argv) > 3 else "both"
        models = ["quick", "full"] if which == "both" else [which]
        for m in models:
            print(json.dumps(conv_trajectory_compare(m, iters)))
    else:
        iters = int(sys.argv[1]) if len(sys.argv) > 1 else 500
        for row in run_all(iters):
            print(json.dumps(row))
