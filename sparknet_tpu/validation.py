"""Numerical validation of the training engine against the reference's
update math, at float64, over long horizons.

"Caffe layer/solver semantics preserved" must be demonstrated, not
asserted: this module runs the framework's jitted Solver next to an
INDEPENDENT NumPy implementation of the reference's forward/backward/update
pipeline (the formulas in caffe/src/caffe/solvers/*.cpp and
softmax_loss_layer.cpp, re-derived here by hand — not a port of the
framework's own jax code) on an identical fixed data stream, and reports
per-iteration loss/parameter drift.  At float64 any semantic difference
(wrong momentum formulation, wrong LR schedule, wrong regularizer order)
shows up as super-rounding-level divergence within a few iterations.

The model is the smallest net that exercises the full pipeline —
InnerProduct + SoftmaxWithLoss — so the hand NumPy gradient is exact:
  logits = x_flat @ W.T + b                 (inner_product_layer.cpp:46-60)
  L = -mean(log softmax(logits)[label])     (softmax_loss_layer.cpp:74-80)
  dlogits = (softmax - onehot) / N          (softmax_loss_layer.cpp:105-120)
  dW = dlogits.T @ x_flat ; db = sum dlogits
then weight decay (sgd_solver.cpp:119-160), LR policy (sgd_solver.cpp:27-64)
and the per-solver update (solvers/*.cpp) are applied in the reference's
order.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

SOLVER_HYPERS: Dict[str, Dict[str, float]] = {
    # per-type hyperparameters in the reference's customary ranges
    "SGD": dict(base_lr=0.05, momentum=0.9),
    "Nesterov": dict(base_lr=0.05, momentum=0.9),
    "AdaGrad": dict(base_lr=0.05, momentum=0.0, delta=1e-8),
    "RMSProp": dict(base_lr=0.01, momentum=0.0, rms_decay=0.98, delta=1e-8),
    "AdaDelta": dict(base_lr=1.0, momentum=0.95, delta=1e-6),
    "Adam": dict(base_lr=0.01, momentum=0.9, momentum2=0.999, delta=1e-8),
}


def _lr(base_lr: float, policy: str, it: int, *, gamma: float = 0.0001,
        power: float = 0.75, stepsize: int = 100) -> float:
    """LR policies, re-derived from sgd_solver.cpp:27-64."""
    if policy == "fixed":
        return base_lr
    if policy == "inv":
        return base_lr * (1.0 + gamma * it) ** (-power)
    if policy == "step":
        return base_lr * (gamma ** (it // stepsize))
    raise ValueError(policy)


class NumpyReferenceSolver:
    """Hand implementation of the reference training iteration at float64."""

    def __init__(self, solver_type: str, w: np.ndarray, b: np.ndarray, *,
                 lr_policy: str = "inv", weight_decay: float = 5e-4,
                 clip: float = 0.0) -> None:
        self.type = solver_type
        self.hy = SOLVER_HYPERS[solver_type]
        self.lr_policy = lr_policy
        self.weight_decay = weight_decay
        self.clip = clip
        self.w = w.astype(np.float64).copy()
        self.b = b.astype(np.float64).copy()
        n_slots = 2 if solver_type in ("AdaDelta", "Adam") else 1
        self.hist = {name: [np.zeros_like(p) for _ in range(n_slots)]
                     for name, p in (("w", self.w), ("b", self.b))}
        self.it = 0

    # ---- forward/backward (inner_product + softmax loss, re-derived)
    def _fwd_bwd(self, x: np.ndarray, y: np.ndarray
                 ) -> Tuple[float, np.ndarray, np.ndarray]:
        n = x.shape[0]
        xf = x.reshape(n, -1).astype(np.float64)
        logits = xf @ self.w.T + self.b
        logits -= logits.max(axis=1, keepdims=True)
        e = np.exp(logits)
        p = e / e.sum(axis=1, keepdims=True)
        loss = float(-np.mean(np.log(np.maximum(p[np.arange(n), y], 1e-300))))
        d = p.copy()
        d[np.arange(n), y] -= 1.0
        d /= n
        return loss, d.T @ xf, d.sum(axis=0)

    def _update_one(self, name: str, p: np.ndarray, g: np.ndarray,
                    lr: float) -> np.ndarray:
        hy = self.hy
        h = self.hist[name]
        t = self.type
        if t == "SGD":
            v = hy["momentum"] * h[0] + lr * g
            h[0] = v
            return p - v
        if t == "Nesterov":
            v_prev = h[0]
            v = hy["momentum"] * v_prev + lr * g
            h[0] = v
            return p - ((1.0 + hy["momentum"]) * v
                        - hy["momentum"] * v_prev)
        if t == "AdaGrad":
            h[0] = h[0] + g * g
            return p - lr * g / (np.sqrt(h[0]) + hy["delta"])
        if t == "RMSProp":
            h[0] = hy["rms_decay"] * h[0] + (1.0 - hy["rms_decay"]) * g * g
            return p - lr * g / (np.sqrt(h[0]) + hy["delta"])
        if t == "AdaDelta":
            mom, delta = hy["momentum"], hy["delta"]
            h[0] = mom * h[0] + (1.0 - mom) * g * g
            upd = g * np.sqrt((delta + h[1]) / (delta + h[0]))
            h[1] = mom * h[1] + (1.0 - mom) * upd * upd
            return p - lr * upd
        if t == "Adam":
            m1, m2 = hy["momentum"], hy["momentum2"]
            step = self.it + 1
            h[0] = m1 * h[0] + (1.0 - m1) * g
            h[1] = m2 * h[1] + (1.0 - m2) * g * g
            corr = np.sqrt(1.0 - m2 ** step) / (1.0 - m1 ** step)
            return p - lr * corr * h[0] / (np.sqrt(h[1]) + hy["delta"])
        raise ValueError(t)

    def step(self, x: np.ndarray, y: np.ndarray) -> float:
        loss, gw, gb = self._fwd_bwd(x, y)
        if self.clip > 0:
            l2 = np.sqrt((gw * gw).sum() + (gb * gb).sum())
            if l2 > self.clip:
                gw, gb = gw * self.clip / l2, gb * self.clip / l2
        # L2 regularization in the reference's order: after clip, before the
        # solver update (sgd_solver.cpp:102-117 ApplyUpdate)
        gw = gw + self.weight_decay * self.w
        gb = gb + self.weight_decay * self.b
        lr = _lr(self.hy["base_lr"], self.lr_policy, self.it)
        self.w = self._update_one("w", self.w, gw, lr)
        self.b = self._update_one("b", self.b, gb, lr)
        self.it += 1
        return loss


def make_stream(iters: int, batch: int = 8, dim: Tuple[int, ...] = (1, 4, 4),
                classes: int = 5, seed: int = 0
                ) -> List[Tuple[np.ndarray, np.ndarray]]:
    rng = np.random.RandomState(seed)
    return [(rng.rand(batch, *dim).astype(np.float64),
             rng.randint(0, classes, size=batch).astype(np.int32))
            for _ in range(iters)]


def trajectory_compare(solver_type: str, iters: int = 500, *,
                       lr_policy: str = "inv", weight_decay: float = 5e-4,
                       clip: float = 0.0, seed: int = 0) -> Dict[str, float]:
    """Run the framework Solver and the NumPy reference side by side at
    float64 on one fixed stream.  Returns drift statistics."""
    import jax

    from .utils.compile_cache import apply_platform_env

    # honor JAX_PLATFORMS=cpu even under a jax-preimporting sitecustomize:
    # TPU backends silently demote f64 to f32, which would turn this
    # double-precision harness into a no-op comparison
    apply_platform_env()
    if jax.default_backend() not in ("cpu",):
        raise RuntimeError(
            "the float64 trajectory harness needs the CPU backend "
            "(set JAX_PLATFORMS=cpu); TPU demotes float64 silently")
    jax.config.update("jax_enable_x64", True)
    try:
        return _trajectory_compare_x64(solver_type, iters,
                                       lr_policy=lr_policy,
                                       weight_decay=weight_decay, clip=clip,
                                       seed=seed)
    finally:
        jax.config.update("jax_enable_x64", False)


def _trajectory_compare_x64(solver_type: str, iters: int, *, lr_policy: str,
                            weight_decay: float, clip: float,
                            seed: int) -> Dict[str, float]:
    import jax.numpy as jnp

    from .proto import caffe_pb
    from .proto.textformat import parse
    from .solver.solver import Solver

    hy = SOLVER_HYPERS[solver_type]
    lines = [f"base_lr: {hy['base_lr']}", f'lr_policy: "{lr_policy}"',
             'gamma: 0.0001', 'power: 0.75', 'stepsize: 100',
             f"weight_decay: {weight_decay}", f'type: "{solver_type}"',
             'random_seed: 11']
    if clip > 0:
        lines.append(f"clip_gradients: {clip}")
    for key, field in (("momentum", "momentum"), ("delta", "delta"),
                       ("momentum2", "momentum2"),
                       ("rms_decay", "rms_decay")):
        if key in hy:
            lines.append(f"{field}: {hy[key]}")
    net_txt = """
name: "tiny"
layer { name: "data" type: "MemoryData" top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 4 width: 4 } }
layer { name: "ip" type: "InnerProduct" bottom: "data" top: "ip"
  inner_product_param { num_output: 5
    weight_filler { type: "gaussian" std: 0.3 }
    bias_filler { type: "constant" value: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" bottom: "label"
  top: "loss" }
"""
    sp = caffe_pb.SolverParameter(parse("\n".join(lines)))
    sp.msg.set("net_param", caffe_pb.parse_net_text(net_txt).msg)
    solver = Solver(sp)
    # promote the framework solver to float64 end to end
    solver.params = {k: jnp.asarray(np.asarray(v), jnp.float64)
                     for k, v in solver.params.items()}
    solver.state = {k: tuple(jnp.asarray(np.asarray(h), jnp.float64)
                             for h in v)
                    for k, v in solver.state.items()}

    wkey, bkey = "ip/0", "ip/1"  # blob 0 = weight, blob 1 = bias
    ref = NumpyReferenceSolver(solver_type,
                               np.asarray(solver.params[wkey]),
                               np.asarray(solver.params[bkey]),
                               lr_policy=lr_policy,
                               weight_decay=weight_decay, clip=clip)

    stream = make_stream(iters, seed=seed)
    idx = {"i": 0}

    def source():
        x, y = stream[idx["i"] % len(stream)]
        idx["i"] += 1
        return {"data": x, "label": y}

    solver.set_train_data(source)

    max_loss_diff = 0.0
    losses_fw: List[float] = []
    losses_ref: List[float] = []
    for i in range(iters):
        # step the framework one iteration (its pull consumes stream[i])
        solver.step(1)
        loss_fw = solver._loss_window[-1]
        x, y = stream[i]
        loss_ref = ref.step(x, y)
        losses_fw.append(loss_fw)
        losses_ref.append(loss_ref)
        max_loss_diff = max(max_loss_diff, abs(loss_fw - loss_ref))

    w_fw = np.asarray(solver.params[wkey])
    b_fw = np.asarray(solver.params[bkey])
    denom = max(np.abs(ref.w).max(), 1e-12)
    return dict(
        solver=solver_type,
        iters=iters,
        max_loss_abs_diff=max_loss_diff,
        final_loss_framework=losses_fw[-1],
        final_loss_reference=losses_ref[-1],
        max_w_rel_diff=float(np.abs(w_fw - ref.w).max() / denom),
        max_b_abs_diff=float(np.abs(b_fw - ref.b).max()),
    )


def run_all(iters: int = 500) -> List[Dict[str, float]]:
    return [trajectory_compare(t, iters) for t in SOLVER_HYPERS]


if __name__ == "__main__":
    import json
    import sys

    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 500
    for row in run_all(iters):
        print(json.dumps(row))
