"""Fused Pallas TPU kernel for ACROSS_CHANNELS LRN, forward + backward.

The XLA formulation in ops/lrn.py (reduce_window of x^2 + pow) materializes
the squared-sum and the pow intermediate in HBM, and the cross-channel window
runs over a non-minor axis of the NCHW layout.  This kernel keeps one
(C, lane-block) tile resident in VMEM, computes the channel-window sum as
`local_size` shifted adds on the VPU, and fuses the scale/pow/multiply — one
HBM read and one write per tensor per pass.  The backward pass fuses the
reference's two-pass gradient (reference: caffe/src/caffe/layers/
lrn_layer.cpp CrossChannelBackward_cpu — ratio accumulation then
axpy) the same way.

Standalone on a v5e chip (AlexNet norm1, 256x96x55x55 bf16) this measures
fwd 1.9ms vs 4.2ms and fwd+bwd 4.4ms vs 6.1ms against the reduce_window
formulation; inside a full train step the difference disappears into the
bench chip's run-to-run variance, so selection is opt-in via
SPARKNET_LRN_IMPL=pallas (see ops/lrn.py dispatch).

Math (reference: lrn_layer.cpp:88-119 CrossChannelForward_cpu):
    scale_i = k + alpha/n * sum_{j in win(i)} x_j^2
    y_i     = x_i * scale_i^{-beta}
    dx_i    = dy_i * scale_i^{-beta}
              - (2*alpha*beta/n) * x_i * sum_{j in rev(i)} dy_j y_j / scale_j
where win(i) = [i-pad_lo, i+pad_hi], pad_lo = (n-1)//2, and rev(i) is the
transpose window [i-pad_hi, i+pad_lo].
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .lrn import _powm  # sqrt/rsqrt fast paths for the models' beta values

LANE_BLOCK = 1024  # spatial lanes per grid cell; C*LANE_BLOCK*4B stays << VMEM


def _window_sum(v: jax.Array, pad_lo: int, pad_hi: int) -> jax.Array:
    """Sum over a [i-pad_lo, i+pad_hi] channel window via shifted adds.

    v is (C, L); the window runs over the sublane (C) axis.
    """
    n = pad_lo + pad_hi + 1
    padded = jnp.pad(v, ((pad_lo, pad_hi), (0, 0)))
    c = v.shape[0]
    acc = padded[0:c]
    for off in range(1, n):
        acc = acc + padded[off:off + c]
    return acc


def _fwd_kernel(x_ref, y_ref, *, pad_lo, pad_hi, alpha, beta, k, n):
    x = x_ref[0].astype(jnp.float32)
    scale = k + (alpha / n) * _window_sum(x * x, pad_lo, pad_hi)
    y = x * _powm(scale, -beta)
    y_ref[0] = y.astype(y_ref.dtype)


def _bwd_kernel(x_ref, dy_ref, dx_ref, *, pad_lo, pad_hi, alpha,
                beta, k, n):
    # scale is recomputed rather than saved: one extra VPU window-sum beats
    # writing+reading a full-tensor f32 residual through HBM (measured: the
    # saved-scale variant was net slower than the XLA path on AlexNet)
    x = x_ref[0].astype(jnp.float32)
    scale = k + (alpha / n) * _window_sum(x * x, pad_lo, pad_hi)
    dy = dy_ref[0].astype(jnp.float32)
    inv_pow = _powm(scale, -beta)
    # ratio r_j = dy_j * y_j / scale_j, accumulated over the transpose window
    ratio = dy * x * _powm(scale, -beta - 1.0)
    acc = _window_sum(ratio, pad_hi, pad_lo)
    dx = dy * inv_pow - (2.0 * alpha * beta / n) * x * acc
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _grid_call(kernel, inputs, out_shapes, shape: Tuple[int, int, int],
               interpret: bool):
    b, c, hw = shape
    bl = min(LANE_BLOCK, pl.cdiv(hw, 128) * 128)
    spec = pl.BlockSpec((1, c, bl), lambda i, j: (i, 0, j))
    return pl.pallas_call(
        kernel,
        grid=(b, pl.cdiv(hw, bl)),
        in_specs=[spec] * len(inputs),
        out_specs=[spec] * len(out_shapes),
        out_shape=out_shapes,
        interpret=interpret,
    )(*inputs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_across_channels_pallas(x: jax.Array, local_size: int = 5,
                               alpha: float = 1.0, beta: float = 0.75,
                               k: float = 1.0,
                               interpret: bool = False) -> jax.Array:
    y, _ = _lrn_fwd(x, local_size, alpha, beta, k, interpret)
    return y


def _lrn_fwd(x, local_size, alpha, beta, k, interpret):
    b, c, h, w = x.shape
    hw = h * w
    pad_lo = (local_size - 1) // 2
    pad_hi = local_size - 1 - pad_lo
    kern = functools.partial(_fwd_kernel, pad_lo=pad_lo, pad_hi=pad_hi,
                             alpha=alpha, beta=beta, k=k, n=local_size)
    (y,) = _grid_call(
        kern, [x.reshape(b, c, hw)],
        [jax.ShapeDtypeStruct((b, c, hw), x.dtype)],
        (b, c, hw), interpret)
    return y.reshape(b, c, h, w), (x,)


def _lrn_bwd(local_size, alpha, beta, k, interpret, res, dy):
    (x,) = res
    b, c, h, w = x.shape
    hw = h * w
    pad_lo = (local_size - 1) // 2
    pad_hi = local_size - 1 - pad_lo
    kern = functools.partial(_bwd_kernel, pad_lo=pad_lo, pad_hi=pad_hi,
                             alpha=alpha, beta=beta, k=k, n=local_size)
    (dx,) = _grid_call(
        kern, [x.reshape(b, c, hw), dy.reshape(b, c, hw)],
        [jax.ShapeDtypeStruct((b, c, hw), x.dtype)],
        (b, c, hw), interpret)
    return (dx.reshape(b, c, h, w),)


lrn_across_channels_pallas.defvjp(
    lambda x, local_size, alpha, beta, k, interpret:
        _lrn_fwd(x, local_size, alpha, beta, k, interpret),
    _lrn_bwd)


def pallas_lrn_supported(x: jax.Array) -> bool:
    """Tile-alignment check: the channel axis sits on sublanes, so it must be
    a multiple of the dtype's sublane tile (8 for f32, 16 for bf16)."""
    if x.ndim != 4:
        return False
    c = x.shape[1]
    sub = 16 if x.dtype == jnp.bfloat16 else 8
    return c % sub == 0 and x.dtype in (jnp.float32, jnp.bfloat16)
