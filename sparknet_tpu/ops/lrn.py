"""Local Response Normalization (reference: caffe/src/caffe/layers/lrn_layer.cpp).

AlexNet/CaffeNet/cifar10_full all use ACROSS_CHANNELS LRN; GoogLeNet uses it
twice.  y = x / (k + alpha/n * sum_window x^2)^beta, where the window is
`local_size` wide over channels (ACROSS_CHANNELS) or over space
(WITHIN_CHANNEL, which the reference computes via average pooling of x^2 —
lrn_layer.cpp:121-135 — so alpha is NOT divided by the window size again).

Expressed with `lax.reduce_window` over the channel axis so XLA keeps it
fused; no custom kernel needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .pooling import avg_pool


def lrn_across_channels(x: jax.Array, local_size: int = 5, alpha: float = 1.0,
                        beta: float = 0.75, k: float = 1.0) -> jax.Array:
    pad = (local_size - 1) // 2
    sq_sum = lax.reduce_window(
        x * x, 0.0, lax.add,
        window_dimensions=(1, local_size, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (pad, local_size - 1 - pad), (0, 0), (0, 0)))
    scale = k + (alpha / local_size) * sq_sum
    return x * jnp.power(scale, -beta)


def lrn_within_channel(x: jax.Array, local_size: int = 5, alpha: float = 1.0,
                       beta: float = 0.75, k: float = 1.0) -> jax.Array:
    pad = (local_size - 1) // 2
    # reference uses AVE pooling of x^2 (divisor = window size incl. padding)
    mean_sq = avg_pool(x * x, (local_size, local_size), stride=(1, 1),
                       pad=(pad, pad))
    # pooling with ceil-mode may add a trailing output; within-channel LRN is
    # stride-1 same-size, so shapes already match.
    mean_sq = mean_sq[:, :, :x.shape[2], :x.shape[3]]
    scale = k + alpha * mean_sq
    return x * jnp.power(scale, -beta)


def lrn(x: jax.Array, local_size: int = 5, alpha: float = 1.0,
        beta: float = 0.75, k: float = 1.0,
        norm_region: str = "ACROSS_CHANNELS") -> jax.Array:
    if norm_region == "ACROSS_CHANNELS":
        return lrn_across_channels(x, local_size, alpha, beta, k)
    return lrn_within_channel(x, local_size, alpha, beta, k)
