"""Local Response Normalization (reference: caffe/src/caffe/layers/lrn_layer.cpp).

AlexNet/CaffeNet/cifar10_full all use ACROSS_CHANNELS LRN; GoogLeNet uses it
twice.  y = x / (k + alpha/n * sum_window x^2)^beta, where the window is
`local_size` wide over channels (ACROSS_CHANNELS) or over space
(WITHIN_CHANNEL, which the reference computes via average pooling of x^2 —
lrn_layer.cpp:121-135 — so alpha is NOT divided by the window size again).

Three implementations of the ACROSS_CHANNELS path, selectable via
SPARKNET_LRN_IMPL=xla|pallas|matmul (default: xla):
- xla: `lax.reduce_window` over the channel axis, with sqrt/rsqrt fast
  paths for the beta the bundled models use (every model runs beta=0.75 and
  scale^-0.75 = rsqrt(scale*sqrt(scale)) — far cheaper than the exp/log
  pow lowering);
- pallas: fused VMEM-resident kernel with a fused custom-VJP backward
  (pallas_lrn.py) — 1.4-2.2x the reduce_window formulation standalone on
  v5e (fwd 1.9ms vs 4.2ms on AlexNet norm1 bf16);
- matmul: the channel window sum as a banded (C, C) matmul on the MXU.
Measured inside a full AlexNet train step on the shared bench chip, the
three are within run-to-run variance of each other, so the portable one is
the default; the standalone-kernel wins are real (see tests + bench notes).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .pooling import avg_pool


def _powm(s: jax.Array, p: float) -> jax.Array:
    """s**p for s>0, avoiding exp/log for the exponents the models use.

    Every bundled model runs beta=0.75, so the hot exponents are -0.75 and
    (backward) -1.75; sqrt/rsqrt are far cheaper than the exp+log pair on
    the VPU and this is where a compute-bound LRN spends its time."""
    if p == -0.75:
        return jax.lax.rsqrt(s * jnp.sqrt(s))
    if p == -1.75:
        return jax.lax.rsqrt(s * jnp.sqrt(s)) / s
    if p == -0.5:
        return jax.lax.rsqrt(s)
    if p == -1.0:
        return 1.0 / s
    return jnp.exp(p * jnp.log(s))


def lrn_across_channels(x: jax.Array, local_size: int = 5, alpha: float = 1.0,
                        beta: float = 0.75, k: float = 1.0) -> jax.Array:
    pad = (local_size - 1) // 2
    sq_sum = lax.reduce_window(
        x * x, 0.0, lax.add,
        window_dimensions=(1, local_size, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (pad, local_size - 1 - pad), (0, 0), (0, 0)))
    scale = k + (alpha / local_size) * sq_sum
    return x * _powm(scale, -beta)


def _band_matrix(c: int, local_size: int, dtype) -> jnp.ndarray:
    """Band[j, i] = 1 where j is inside output channel i's window."""
    pad_lo = (local_size - 1) // 2
    i = np.arange(c)
    band = ((i[None, :] - pad_lo <= i[:, None])
            & (i[:, None] <= i[None, :] + (local_size - 1 - pad_lo)))
    return jnp.asarray(band.astype(np.float32), dtype=dtype)


def lrn_across_channels_matmul(x: jax.Array, local_size: int = 5,
                               alpha: float = 1.0, beta: float = 0.75,
                               k: float = 1.0) -> jax.Array:
    """The channel-window sum as a banded (C, C) matmul.

    On TPU the window reduction of the reduce_window/pallas formulations is
    VPU- and layout-bound while the MXU sits idle; a 0/1 banded matmul over
    the channel axis moves it onto the MXU (~0.04 ms for AlexNet norm1 vs
    milliseconds on the VPU) and is exactly autodifferentiable (the
    transpose is the reflected band).  Works for any channel count/dtype."""
    c = x.shape[1]
    band = _band_matrix(c, local_size, x.dtype)
    sq_sum = jnp.einsum("nchw,cd->ndhw", x * x, band,
                        preferred_element_type=jnp.float32).astype(x.dtype)
    scale = k + (alpha / local_size) * sq_sum
    return x * _powm(scale, -beta)


def lrn_within_channel(x: jax.Array, local_size: int = 5, alpha: float = 1.0,
                       beta: float = 0.75, k: float = 1.0) -> jax.Array:
    pad = (local_size - 1) // 2
    # reference uses AVE pooling of x^2 (divisor = window size incl. padding)
    mean_sq = avg_pool(x * x, (local_size, local_size), stride=(1, 1),
                       pad=(pad, pad))
    # pooling with ceil-mode may add a trailing output; within-channel LRN is
    # stride-1 same-size, so shapes already match.
    mean_sq = mean_sq[:, :, :x.shape[2], :x.shape[3]]
    scale = k + alpha * mean_sq
    return x * _powm(scale, -beta)


def _pick_impl() -> str:
    impl = os.environ.get("SPARKNET_LRN_IMPL")
    if impl is None:
        # Measured on v5e (scripts/googlenet_profile.py): the banded-matmul
        # formulation rides the MXU and lifts the full GoogLeNet train step
        # ~40% over the rolling-window XLA one (3.05k -> 4.26k img/s b64);
        # elsewhere (CPU tests) the windowed formulation stays default.
        return "matmul" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("xla", "pallas", "matmul"):
        raise ValueError(
            f"SPARKNET_LRN_IMPL={impl!r}; expected xla, pallas, or matmul")
    return impl


def lrn(x: jax.Array, local_size: int = 5, alpha: float = 1.0,
        beta: float = 0.75, k: float = 1.0,
        norm_region: str = "ACROSS_CHANNELS") -> jax.Array:
    if norm_region == "ACROSS_CHANNELS":
        impl = _pick_impl()
        if impl == "matmul":
            return lrn_across_channels_matmul(x, local_size, alpha, beta, k)
        if impl == "pallas":
            # deferred: keeps jax.experimental.pallas out of the default path
            from .pallas_lrn import (lrn_across_channels_pallas,
                                     pallas_lrn_supported)
            if pallas_lrn_supported(x):
                interpret = jax.default_backend() != "tpu"
                return lrn_across_channels_pallas(x, local_size, alpha, beta,
                                                  k, interpret)
        return lrn_across_channels(x, local_size, alpha, beta, k)
    return lrn_within_channel(x, local_size, alpha, beta, k)
