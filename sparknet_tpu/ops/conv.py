"""Convolution ops (logical NCHW, OIHW weights — Caffe blob shapes).

The reference lowers conv via im2col+GEMM with hand-written CUDA
(reference: caffe/src/caffe/layers/base_conv_layer.cpp,
caffe/src/caffe/util/im2col.cu).  On TPU we hand the whole convolution to XLA
(`lax.conv_general_dilated`), which tiles it directly onto the MXU — there is
no im2col materialization and no custom kernel needed.  Weight layout OIHW
matches Caffe's `(num_output, channels/group, kh, kw)` blob so weight
interchange and per-blob lr_mult semantics carry over unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_DIMSPEC = ("NCHW", "OIHW", "NCHW")


def conv2d(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None, *,
           stride: Tuple[int, int] = (1, 1), pad: Tuple[int, int] = (0, 0),
           dilation: Tuple[int, int] = (1, 1), groups: int = 1) -> jax.Array:
    """Forward conv (reference semantics: caffe/src/caffe/layers/conv_layer.cpp:
    output dim = (in + 2*pad - dilation*(k-1) - 1) / stride + 1, floor)."""
    y = lax.conv_general_dilated(
        x, w,
        window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=dilation,
        dimension_numbers=_DIMSPEC,
        feature_group_count=groups,
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


def deconv2d(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None, *,
             stride: Tuple[int, int] = (1, 1), pad: Tuple[int, int] = (0, 0),
             dilation: Tuple[int, int] = (1, 1), groups: int = 1) -> jax.Array:
    """Deconvolution = conv backward-data pass as a forward op
    (reference: caffe/src/caffe/layers/deconv_layer.cpp — "convolution with
    forward and backward swapped").  Output dim =
    stride*(in-1) + dilation*(k-1) + 1 - 2*pad.

    Weight blob shape follows Caffe: (channels_in, num_output/group, kh, kw).
    Implemented as input-dilated ("fractionally strided") convolution with a
    spatially-flipped, transposed kernel — exactly what conv backward-data is.
    """
    ci, cog, kh, kw = w.shape
    # (in, out/group, kh, kw) -> flip spatial, swap to (out, in/group, kh, kw)
    wt = w[:, :, ::-1, ::-1]
    if groups == 1:
        wt = jnp.transpose(wt, (1, 0, 2, 3))
    else:
        wt = wt.reshape(groups, ci // groups, cog, kh, kw)
        wt = jnp.transpose(wt, (0, 2, 1, 3, 4)).reshape(groups * cog,
                                                        ci // groups, kh, kw)
    eff_kh = dilation[0] * (kh - 1) + 1
    eff_kw = dilation[1] * (kw - 1) + 1
    y = lax.conv_general_dilated(
        x, wt,
        window_strides=(1, 1),
        padding=[(eff_kh - 1 - pad[0], eff_kh - 1 - pad[0]),
                 (eff_kw - 1 - pad[1], eff_kw - 1 - pad[1])],
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=_DIMSPEC,
        feature_group_count=groups,
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


def conv_out_dim(size: int, kernel: int, pad: int, stride: int,
                 dilation: int = 1) -> int:
    return (size + 2 * pad - dilation * (kernel - 1) - 1) // stride + 1


def deconv_out_dim(size: int, kernel: int, pad: int, stride: int,
                   dilation: int = 1) -> int:
    return stride * (size - 1) + dilation * (kernel - 1) + 1 - 2 * pad


def im2col(x: jax.Array, kernel: Tuple[int, int], *,
           stride: Tuple[int, int] = (1, 1), pad: Tuple[int, int] = (0, 0),
           dilation: Tuple[int, int] = (1, 1)) -> jax.Array:
    """The Im2col *layer* (reference: caffe/src/caffe/layers/im2col_layer.cpp):
    (N,C,H,W) -> (N, C*kh*kw, out_h, out_w).  Provided for layer-zoo parity;
    conv itself never calls this on TPU."""
    n, c, h, wd = x.shape
    kh, kw = kernel
    oh = conv_out_dim(h, kh, pad[0], stride[0], dilation[0])
    ow = conv_out_dim(wd, kw, pad[1], stride[1], dilation[1])
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    cols = []
    for i in range(kh):
        for j in range(kw):
            di, dj = i * dilation[0], j * dilation[1]
            patch = lax.slice(
                xp, (0, 0, di, dj),
                (n, c, di + (oh - 1) * stride[0] + 1,
                 dj + (ow - 1) * stride[1] + 1),
                (1, 1, stride[0], stride[1]))
            cols.append(patch)
    # (kh*kw, N, C, oh, ow) -> (N, C, kh*kw, oh, ow) -> (N, C*kh*kw, oh, ow)
    stacked = jnp.stack(cols, axis=2)
    return stacked.reshape(n, c * kh * kw, oh, ow)
