"""TPU-native layer zoo: pure JAX functions replacing the reference's C++/CUDA
layer implementations (reference: caffe/src/caffe/layers/ — 58 .cpp + 44 .cu).
XLA:TPU codegen replaces the hand-written kernels; there is deliberately no
Layer class hierarchy — composition happens in core.net."""

from .activations import (absval, bnll, dropout, exp, log, power, prelu, relu,
                          sigmoid, tanh, threshold)
from .attention import (attention, blockwise_attention,
                        flash_attention_tpu)
from .conv import conv2d, conv_out_dim, deconv2d, deconv_out_dim, im2col
from .dense import embed, inner_product
from .fused_block import (fused_blocks_mode, fused_conv_lrn_pool,
                          fused_out_shape, fused_tail_supported)
from .lrn import lrn, lrn_across_channels, lrn_within_channel
from .moe import expert_capacity, moe_ffn, top_k_gating
from .losses import (accuracy, argmax, contrastive_loss, euclidean_loss,
                     hinge_loss, infogain_loss, multinomial_logistic_loss,
                     sigmoid_cross_entropy_loss, softmax, softmax_with_loss)
from .norm import batch_norm, mvn, scale_shift
from .pooling import (avg_pool, global_pool, max_pool, pool_out_dim, spp,
                      stochastic_pool)
from .shape_ops import (batch_reindex, concat, eltwise, filter_op, flatten,
                        reduction, reshape, silence, slice_op, split, tile)
