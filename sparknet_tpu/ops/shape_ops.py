"""Structural / utility ops: concat, slice, split, flatten, reshape, eltwise,
tile, reduction, batch_reindex, filter, silence
(reference: caffe/src/caffe/layers/{concat,slice,split,flatten,reshape,
eltwise,tile,reduction,batch_reindex,filter,silence}_layer.cpp).

These are shape plumbing — XLA folds them into the surrounding computation.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp


def concat(xs: Sequence[jax.Array], axis: int = 1) -> jax.Array:
    return jnp.concatenate(list(xs), axis=axis)


def slice_op(x: jax.Array, *, axis: int = 1,
             slice_points: Optional[Sequence[int]] = None,
             num_slices: Optional[int] = None) -> List[jax.Array]:
    """reference: slice_layer.cpp:40-60 — explicit slice_points or equal split."""
    size = x.shape[axis]
    if slice_points:
        points = list(slice_points)
    else:
        assert num_slices is not None and size % num_slices == 0
        step = size // num_slices
        points = [step * i for i in range(1, num_slices)]
    bounds = [0] + points + [size]
    return [jax.lax.slice_in_dim(x, bounds[i], bounds[i + 1], axis=axis)
            for i in range(len(bounds) - 1)]


def split(x: jax.Array, n: int) -> List[jax.Array]:
    """Fan-out: the reference's Split layer shares data to n tops
    (split_layer.cpp); functionally it's just reuse of the same value."""
    return [x] * n


def flatten(x: jax.Array, *, axis: int = 1, end_axis: int = -1) -> jax.Array:
    nd = x.ndim
    a = axis % nd
    e = end_axis % nd
    mid = 1
    for s in x.shape[a:e + 1]:
        mid *= s
    return x.reshape(x.shape[:a] + (mid,) + x.shape[e + 1:])


def reshape(x: jax.Array, dims: Sequence[int], *, axis: int = 0,
            num_axes: int = -1) -> jax.Array:
    """reference: reshape_layer.cpp — dim 0 copies the input dim, -1 infers."""
    nd = x.ndim
    a = axis % (nd + 1) if axis >= 0 else nd + 1 + axis
    end = nd if num_axes == -1 else a + num_axes
    spanned = x.shape[a:end]
    out_mid: List[int] = []
    infer = -1
    for i, d in enumerate(dims):
        if d == 0:
            out_mid.append(spanned[i])
        elif d == -1:
            infer = len(out_mid)
            out_mid.append(1)
        else:
            out_mid.append(int(d))
    new_shape = list(x.shape[:a]) + out_mid + list(x.shape[end:])
    if infer >= 0:
        known = 1
        for s in new_shape:
            known *= s
        total = 1
        for s in x.shape:
            total *= s
        new_shape[a + infer] = total // known
    return x.reshape(tuple(new_shape))


def eltwise(xs: Sequence[jax.Array], *, operation: str = "SUM",
            coeffs: Optional[Sequence[float]] = None) -> jax.Array:
    """reference: eltwise_layer.cpp:28-70 (PROD, SUM with coeffs, MAX)."""
    if operation == "PROD":
        out = xs[0]
        for x in xs[1:]:
            out = out * x
        return out
    if operation == "MAX":
        out = xs[0]
        for x in xs[1:]:
            out = jnp.maximum(out, x)
        return out
    cs = list(coeffs) if coeffs else [1.0] * len(xs)
    out = xs[0] * cs[0]
    for x, c in zip(xs[1:], cs[1:]):
        out = out + x * c
    return out


def tile(x: jax.Array, *, axis: int = 1, tiles: int = 1) -> jax.Array:
    reps = [1] * x.ndim
    reps[axis % x.ndim] = tiles
    return jnp.tile(x, reps)


def reduction(x: jax.Array, *, operation: str = "SUM", axis: int = 0,
              coeff: float = 1.0) -> jax.Array:
    """Reduce trailing axes from `axis` on (reference: reduction_layer.cpp)."""
    n = x.ndim
    a = axis % n
    lead = x.shape[:a]
    flat = x.reshape(lead + (-1,)) if a < n else x.reshape(lead)
    if operation == "SUM":
        out = jnp.sum(flat, axis=-1)
    elif operation == "ASUM":
        out = jnp.sum(jnp.abs(flat), axis=-1)
    elif operation == "SUMSQ":
        out = jnp.sum(flat * flat, axis=-1)
    elif operation == "MEAN":
        out = jnp.mean(flat, axis=-1)
    else:
        raise ValueError(f"unknown reduction {operation}")
    return out * coeff


def batch_reindex(x: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather along the batch axis (reference: batch_reindex_layer.cpp)."""
    return x[idx.astype(jnp.int32)]


def filter_op(xs: Sequence[jax.Array], selector: jax.Array,
              ) -> List[jax.Array]:
    """reference: filter_layer.cpp — keep items whose selector is nonzero.

    Data-dependent output shape cannot be jitted on TPU; this op is provided
    for host-side/eager use (the reference uses it only in deploy-side nets).
    """
    keep = jnp.nonzero(selector.reshape(-1))[0]
    return [x[keep] for x in xs]


def silence(*xs: jax.Array) -> None:
    """Consume inputs, produce nothing (reference: silence_layer.cpp)."""
    return None
