"""Subprocess compile-probe for the shipped Pallas flash-attention kernel.

The kernel (`jax.experimental.pallas.ops.tpu.flash_attention`) can HANG at
compile on some platforms — observed on this project's tunneled dev TPU,
where the in-process hang also wedged the tunnel server-side for hours
(BENCH_NOTES.md incident).  A hang is not an exception, so no in-process
try/except can guard it; the only safe shape is the one `bench.py`'s device
guard already uses: run the compile ONCE in a child process under a hard
timeout, kill the child if it blows the budget, and cache the verdict so the
cost (and, on wedge-prone platforms, the risk) is paid at most once per
(platform, jax version).

`flash_attention_tpu` consults this probe before ever importing the kernel
in-process; a negative or timed-out probe silently selects the XLA
blockwise-attention fallback.  The host process can therefore never hang,
whatever `SPARKNET_FLASH_ATTENTION` is set to (VERDICT r2 item 3).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

PROBE_OK_MARKER = "FLASH_PROBE_OK"

# Compiles (does not run) the kernel on a representative shape: compilation
# is where the observed hang lives, and .compile() exercises the full
# Mosaic/XLA pipeline without touching training state.
_PROBE_CODE = f"""
import jax, jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention
q = jnp.zeros((1, 2, 256, 64), jnp.float32)
jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                        sm_scale=0.125)).lower(
    q, q, q).compile()
print("{PROBE_OK_MARKER}")
"""

DEFAULT_TIMEOUT_S = 300.0  # first TPU compiles are 20-40s; 5 min is a hang

# per-process memo so a jitted model tracing many attention layers consults
# the disk cache (and certainly the subprocess) at most once
_memo: Dict[str, bool] = {}


def _default_cache_path() -> str:
    import jax

    platform = jax.devices()[0].platform
    base = os.environ.get(
        "SPARKNET_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "sparknet_tpu_cache"))
    return os.path.join(
        base, f"flash_probe_{platform}_jax{jax.__version__}.json")


def clear_probe_cache(cache_path: Optional[str] = None) -> None:
    """Drop the memo and the on-disk verdict (tests; or after a platform
    fix, to let the probe re-run)."""
    path = cache_path or _default_cache_path()
    _memo.pop(path, None)
    try:
        os.remove(path)
    except OSError:
        pass


def probe_flash_kernel(*, timeout_s: Optional[float] = None,
                       cache_path: Optional[str] = None,
                       probe_cmd: Optional[List[str]] = None) -> bool:
    """True iff the Pallas flash-attention kernel compiles in a child
    process within `timeout_s`.  The verdict — positive OR negative — is
    cached at `cache_path`; a timed-out probe is never retried implicitly
    (retrying is exactly how the platform re-wedges), use
    `clear_probe_cache()` to force a re-probe.

    `probe_cmd` overrides the child command (tests fake a hanging compile
    with a `sleep` child and assert the timeout kills it)."""
    path = cache_path or _default_cache_path()
    if path in _memo:
        return _memo[path]
    try:
        with open(path) as f:
            verdict = bool(json.load(f)["ok"])
        _memo[path] = verdict
        return verdict
    except (OSError, ValueError, KeyError):
        pass

    forced = os.environ.get("SPARKNET_FLASH_PROBE_RESULT")
    if forced in ("ok", "fail"):
        # operator override for platforms where no child process can ever
        # acquire the accelerator next to the trainer (exclusive per-
        # process TPU lock): smoke-test once standalone, then pin "ok"
        _memo[path] = forced == "ok"
        return _memo[path]

    if timeout_s is None:
        timeout_s = float(os.environ.get("SPARKNET_FLASH_PROBE_TIMEOUT",
                                         DEFAULT_TIMEOUT_S))
    cmd = probe_cmd or [sys.executable, "-c", _PROBE_CODE]
    detail = ""
    cache_verdict = True
    try:
        # subprocess.run kills the child on TimeoutExpired before raising,
        # so a hung compile cannot outlive the probe
        r = subprocess.run(cmd, timeout=timeout_s, capture_output=True)
        stderr = r.stderr.decode(errors="replace")
        ok = (r.returncode == 0
              and PROBE_OK_MARKER in r.stdout.decode(errors="replace"))
        if not ok:
            detail = f"exit {r.returncode}: " + stderr[-500:]
            # the child failing to ACQUIRE the device (the parent holds
            # libtpu's exclusive per-process lock) says nothing about the
            # kernel — fall back now but do not poison the disk cache;
            # a standalone run (or SPARKNET_FLASH_PROBE_RESULT=ok after a
            # manual smoke test) can still deliver a real verdict
            acquisition = ("already in use" in stderr
                           or "Device or resource busy" in stderr
                           or "Unable to initialize backend" in stderr
                           or "failed to open" in stderr.lower())
            if acquisition:
                cache_verdict = False
    except subprocess.TimeoutExpired:
        ok = False
        detail = f"compile probe exceeded {timeout_s}s (hang); child killed"
    except OSError as e:
        ok = False
        detail = f"could not launch probe: {e}"
        cache_verdict = False  # transient launch failure, not a verdict

    _memo[path] = ok
    if cache_verdict:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"ok": ok, "detail": detail,
                           "timeout_s": timeout_s}, f)
            os.replace(tmp, path)
        except OSError:
            pass  # uncachable verdict still holds via _memo
    return ok
