"""Neuron (elementwise) ops — reference: caffe/src/caffe/layers/*_layer.cpp.

All are pure jnp functions; XLA fuses them into adjacent matmul/conv HLOs on
TPU, so there is no analogue of the reference's per-layer CUDA kernels.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def relu(x: jax.Array, negative_slope: float = 0.0) -> jax.Array:
    """reference: relu_layer.cpp:9-20 (leaky when negative_slope != 0)."""
    if negative_slope == 0.0:
        return jnp.maximum(x, 0)
    return jnp.where(x > 0, x, negative_slope * x)


def prelu(x: jax.Array, slope: jax.Array, channel_shared: bool = False,
          ) -> jax.Array:
    """reference: prelu_layer.cpp; slope is a learnable per-channel (or
    scalar) blob; x is (N, C, ...)."""
    if channel_shared:
        a = slope.reshape(())
    else:
        a = slope.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x > 0, x, a * x)


def sigmoid(x: jax.Array) -> jax.Array:
    return jax.nn.sigmoid(x)


def tanh(x: jax.Array) -> jax.Array:
    return jnp.tanh(x)


def bnll(x: jax.Array) -> jax.Array:
    """y = log(1 + exp(x)), overflow-safe (reference: bnll_layer.cpp:9-20)."""
    return jnp.logaddexp(0.0, x)


def absval(x: jax.Array) -> jax.Array:
    return jnp.abs(x)


def power(x: jax.Array, power: float = 1.0, scale: float = 1.0,
          shift: float = 0.0) -> jax.Array:
    """y = (shift + scale*x)^power (reference: power_layer.cpp:10-60)."""
    inner = shift + scale * x
    if power == 1.0:
        return inner
    return jnp.power(inner, power)


def exp(x: jax.Array, base: float = -1.0, scale: float = 1.0,
        shift: float = 0.0) -> jax.Array:
    """y = base^(shift + scale*x); base=-1 means e
    (reference: exp_layer.cpp:10-35)."""
    inner = shift + scale * x
    if base == -1.0:
        return jnp.exp(inner)
    return jnp.exp(inner * jnp.log(base))


def log(x: jax.Array, base: float = -1.0, scale: float = 1.0,
        shift: float = 0.0) -> jax.Array:
    """y = log_base(shift + scale*x) (reference: log_layer.cpp:10-45)."""
    inner = shift + scale * x
    y = jnp.log(inner)
    if base != -1.0:
        y = y / jnp.log(base)
    return y


def threshold(x: jax.Array, threshold: float = 0.0) -> jax.Array:
    """y = 1[x > t] (reference: threshold_layer.cpp:9-20). Not differentiable;
    the reference has no Backward either."""
    return (x > threshold).astype(x.dtype)


def dropout(x: jax.Array, ratio: float, rng: Optional[jax.Array],
            train: bool) -> jax.Array:
    """Inverted dropout: train scales kept units by 1/(1-ratio), test is
    identity (reference: dropout_layer.cpp:29-46)."""
    if not train or ratio == 0.0:
        return x
    keep = 1.0 - ratio
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
