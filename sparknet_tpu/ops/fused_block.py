"""Fused conv→ReLU→LRN→max-pool tower block (AlexNet norm1/norm2 stages).

The PHAST Caffe-port lesson (PAPERS.md) is that kernel-by-kernel
translation leaves fusion wins on the table: after ops/pallas_lrn.py the
AlexNet tower stage still runs relu, LRN, and pool as three XLA ops with
three full HBM round-trips of the (N, C, H, W) map.  This module fuses
the memory-bound TAIL (relu → cross-channel LRN → ceil-mode MAX pool)
into one Pallas kernel: the conv itself stays on the MXU via ops.conv2d
(a hand-written VPU conv would forfeit the systolic array), then one
grid cell per batch element keeps the (C, H, W) plane VMEM-resident
(AlexNet norm1: 96·55·55·4B ≈ 1.2 MB) and writes only the pooled output.

Strided pooling inside the kernel dodges Mosaic's strided-slice
rejection (the blocker recorded in ops/pooling.py's study) with a
reshape trick: pad H to a multiple of stride, reshape to
(C, lh, sh, lw, sw), and window offset (i, j) becomes the UNIT-stride
slice r[:, di:di+oh, ri, dj:dj+ow, rj] with (di, ri) = divmod(i, sh).

The backward is a fused custom-VJP kernel following pallas_lrn.py's
template: relu/scale/pool routing are recomputed from the conv output
(one extra VPU pass beats writing f32 residuals through HBM — the
measured lesson in pallas_lrn._bwd_kernel), pool gradients scatter with
first-max-wins tie routing via the stride-residue class maps of
ops.pooling._max_pool_residue_bwd (tree-min over offset indices, one
interleaving reshape), then the LRN transpose window and the relu mask.

Math (reference: caffe/src/caffe/layers/lrn_layer.cpp:88-119 forward,
pooling_layer.cpp:155-169 max routing):
    xr      = relu(x)                      [optional, slope s]
    scale_i = k + alpha/n * sum_{j in win(i)} xr_j^2
    y_i     = xr_i * scale_i^{-beta}
    out     = maxpool(y)                   [ceil mode, -inf padding]

Dispatch: SPARKNET_FUSED_BLOCKS=off|xla|pallas|pallas-tail (mirrors
SPARKNET_LRN_IMPL in ops/lrn.py; consumed by core/net.py's fusion
pass).  `xla` composes the exact stock unfused ops inside one layer fn
(bitwise-identical graph, lets XLA see the whole chain); `pallas`
prefers the full-block implicit-GEMM kernel (ops/pallas_conv.py — conv
on the MXU plus this tail in ONE VMEM residency) where its geometry
gate passes and otherwise uses the tail kernel here; `pallas-tail`
forces the tail-only kernel (the full-block A/B control).  All kernel
modes fall back to the XLA composition gracefully off-TPU — tests
exercise the kernels on CPU via interpret=True.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .activations import relu as _relu_op
from .conv import conv2d
from .lrn import _powm, lrn as _lrn_dispatch
from .pooling import _window_geometry, max_pool, pool_out_dim


def fused_blocks_mode() -> str:
    """SPARKNET_FUSED_BLOCKS=off|xla|pallas|pallas-tail (default off;
    empty/0 = off).  `pallas` prefers the full-block implicit-GEMM
    kernel (ops/pallas_conv.py) where the geometry gate passes and falls
    back to the tail-only kernel; `pallas-tail` forces the tail-only
    kernel everywhere (the A/B control scripts/fullblock_probe.py
    drives)."""
    mode = os.environ.get("SPARKNET_FUSED_BLOCKS")
    if mode in (None, "", "0", "off"):
        return "off"
    if mode not in ("xla", "pallas", "pallas-tail"):
        raise ValueError(
            f"SPARKNET_FUSED_BLOCKS={mode!r}; expected off, xla, pallas, "
            f"or pallas-tail")
    return mode


def effective_fused_blocks_mode() -> str:
    """The mode that will actually execute on this process's backend:
    both pallas modes degrade to the XLA composition off-TPU (the
    graceful-fallback contract), so records stamped with this value are
    attributable — a CPU-mesh A/B run labeled `pallas` would claim a
    kernel that never ran."""
    import jax

    mode = fused_blocks_mode()
    if mode in ("pallas", "pallas-tail") and jax.default_backend() != "tpu":
        return "xla"
    return mode


class _PoolGeom(NamedTuple):
    """Host-side static geometry for the in-kernel reshape-trick pool."""
    h: int
    w: int
    kh: int
    kw: int
    sh: int
    sw: int
    oh: int
    ow: int
    pad_h_lo: int
    pad_w_lo: int
    hp: int   # padded H, a multiple of sh
    wp: int   # padded W, a multiple of sw
    lh: int   # hp // sh
    lw: int   # wp // sw


def _pool_geometry(h: int, w: int, kernel: Tuple[int, int],
                   stride: Tuple[int, int],
                   pad: Tuple[int, int]) -> _PoolGeom:
    kh, kw = kernel
    sh, sw = stride
    oh, ow, pad_h, pad_w = _window_geometry((h, w), kernel, pad, stride)
    # every offset slice r[:, di:di+oh, ri, ...] needs di+oh <= lh with
    # di = (kh-1)//sh at most, so lh >= oh + (kh-1)//sh; same for W
    need_h = max((oh - 1) * sh + kh, h + pad_h[0])
    need_w = max((ow - 1) * sw + kw, w + pad_w[0])
    hp = -(-need_h // sh) * sh
    wp = -(-need_w // sw) * sw
    return _PoolGeom(h, w, kh, kw, sh, sw, oh, ow, pad_h[0], pad_w[0],
                     hp, wp, hp // sh, wp // sw)


def _winsum_c(v: jax.Array, pad_lo: int, pad_hi: int) -> jax.Array:
    """Channel-window sum over axis 0 of (C, H, W) via shifted adds
    (the pallas_lrn._window_sum idea, one extra trailing axis)."""
    c = v.shape[0]
    padded = jnp.pad(v, ((pad_lo, pad_hi), (0, 0), (0, 0)))
    acc = padded[0:c]
    for off in range(1, pad_lo + pad_hi + 1):
        acc = acc + padded[off:off + c]
    return acc


def _apply_relu(x: jax.Array, relu_slope: Optional[float]) -> jax.Array:
    if relu_slope is None:
        return x
    if relu_slope == 0.0:
        return jnp.maximum(x, 0.0)
    return jnp.where(x > 0, x, relu_slope * x)


def _pool_patches(y: jax.Array, g: _PoolGeom):
    """All kh*kw window-offset views of y as unit-stride (C, oh, ow)
    slices of the stride-reshaped padded map (Mosaic-safe)."""
    c = y.shape[0]
    yp = jnp.pad(y, ((0, 0),
                     (g.pad_h_lo, g.hp - g.h - g.pad_h_lo),
                     (g.pad_w_lo, g.wp - g.w - g.pad_w_lo)),
                 constant_values=-jnp.inf)
    r = yp.reshape(c, g.lh, g.sh, g.lw, g.sw)
    patches = []
    for i in range(g.kh):
        di, ri = divmod(i, g.sh)
        for j in range(g.kw):
            dj, rj = divmod(j, g.sw)
            patches.append(r[:, di:di + g.oh, ri, dj:dj + g.ow, rj])
    return patches


def _fused_tail_fwd_kernel(x_ref, y_ref, *, relu_slope, pad_lo, pad_hi,
                           alpha, beta, k, n, geom):
    x = x_ref[0].astype(jnp.float32)
    xr = _apply_relu(x, relu_slope)
    scale = k + (alpha / n) * _winsum_c(xr * xr, pad_lo, pad_hi)
    y = xr * _powm(scale, -beta)
    out = _pool_patches(y, geom)
    acc = out[0]
    for p in out[1:]:
        acc = jnp.maximum(acc, p)
    y_ref[0] = acc.astype(y_ref.dtype)


def _fused_tail_bwd_kernel(x_ref, dy_ref, dx_ref, *, relu_slope, pad_lo,
                           pad_hi, alpha, beta, k, n, geom):
    # recompute relu/scale/y rather than saving them: pallas_lrn's measured
    # lesson — an extra VPU pass beats full-tensor f32 residuals in HBM
    x = x_ref[0].astype(jnp.float32)
    xr = _apply_relu(x, relu_slope)
    scale = k + (alpha / n) * _winsum_c(xr * xr, pad_lo, pad_hi)
    inv_pow = _powm(scale, -beta)
    y = xr * inv_pow
    dy = dy_ref[0].astype(jnp.float32)

    g = geom
    c = x.shape[0]
    patches = _pool_patches(y, g)
    m = patches[0]
    for p in patches[1:]:
        m = jnp.maximum(m, p)
    # first-max-wins tie routing via a parallel tree-min over offset
    # indices, then a stride-residue class-map scatter — the
    # _max_pool_residue_bwd formulation, single batch element
    big = jnp.int32(g.kh * g.kw)
    first = None
    for idx, p in enumerate(patches):
        cand = jnp.where(p == m, jnp.int32(idx), big)
        first = cand if first is None else jnp.minimum(first, cand)
    zero = jnp.zeros((c, g.lh, g.lw), dtype=jnp.float32)
    classes = [[zero] * g.sw for _ in range(g.sh)]
    for i in range(g.kh):
        di, ri = divmod(i, g.sh)
        for j in range(g.kw):
            dj, rj = divmod(j, g.sw)
            idx = i * g.kw + j
            win = (patches[idx] == m) & (first == idx)
            contrib = jnp.where(win, dy, 0.0)
            shifted = jnp.pad(contrib, ((0, 0),
                                        (di, g.lh - g.oh - di),
                                        (dj, g.lw - g.ow - dj)))
            classes[ri][rj] = classes[ri][rj] + shifted
    grid = jnp.stack([jnp.stack(row, axis=-1) for row in classes],
                     axis=-3)  # (c, lh, sh, lw, sw)
    dy_lrn = grid.reshape(c, g.hp, g.wp)[
        :, g.pad_h_lo:g.pad_h_lo + g.h, g.pad_w_lo:g.pad_w_lo + g.w]

    # LRN backward over the transpose window (lrn_layer.cpp:121-156
    # CrossChannelBackward_cpu, fused as in pallas_lrn._bwd_kernel)
    ratio = dy_lrn * xr * _powm(scale, -beta - 1.0)
    acc = _winsum_c(ratio, pad_hi, pad_lo)
    dxr = dy_lrn * inv_pow - (2.0 * alpha * beta / n) * xr * acc
    if relu_slope is None:
        dx = dxr
    else:
        dx = jnp.where(x > 0, dxr, relu_slope * dxr)
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _tail_grid_call(kernel, inputs, out_shape, interpret: bool):
    # deferred: keeps jax.experimental.pallas off the module-import path
    # (the ops.lrn dispatch contract, pinned by tests/test_lrn_dispatch.py)
    from jax.experimental import pallas as pl

    b = inputs[0].shape[0]
    # every operand is (N, C, H-ish, W-ish): one batch element per cell
    specs = [pl.BlockSpec((1,) + tuple(arr.shape[1:]),
                          lambda i: (i, 0, 0, 0)) for arr in inputs]
    out_spec = pl.BlockSpec((1,) + tuple(out_shape.shape[1:]),
                            lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=specs,
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)


# nondiff: (local_size, alpha, beta, k, relu_slope, pool_kernel,
#           pool_stride, pool_pad, interpret)
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(1, 2, 3, 4, 5, 6, 7, 8, 9))
def fused_tail_pallas(x: jax.Array, local_size: int, alpha: float,
                      beta: float, k: float, relu_slope: Optional[float],
                      pool_kernel: Tuple[int, int],
                      pool_stride: Tuple[int, int],
                      pool_pad: Tuple[int, int],
                      interpret: bool = False) -> jax.Array:
    """relu→LRN(ACROSS_CHANNELS)→MAX-pool of a conv output, one kernel.

    relu_slope=None skips the relu stage; pool geometry is Caffe
    ceil-mode (ops.pooling.pool_out_dim).  x is (N, C, H, W)."""
    y, _ = _fused_tail_fwd(x, local_size, alpha, beta, k, relu_slope,
                           pool_kernel, pool_stride, pool_pad, interpret)
    return y


def _fused_tail_fwd(x, local_size, alpha, beta, k, relu_slope,
                    pool_kernel, pool_stride, pool_pad, interpret):
    b, c, h, w = x.shape
    pad_lo = (local_size - 1) // 2
    pad_hi = local_size - 1 - pad_lo
    geom = _pool_geometry(h, w, tuple(pool_kernel), tuple(pool_stride),
                          tuple(pool_pad))
    kern = functools.partial(
        _fused_tail_fwd_kernel, relu_slope=relu_slope, pad_lo=pad_lo,
        pad_hi=pad_hi, alpha=alpha, beta=beta, k=k, n=local_size, geom=geom)
    y = _tail_grid_call(
        kern, [x], jax.ShapeDtypeStruct((b, c, geom.oh, geom.ow), x.dtype),
        interpret)
    return y, (x,)


def _fused_tail_bwd(local_size, alpha, beta, k, relu_slope, pool_kernel,
                    pool_stride, pool_pad, interpret, res, dy):
    (x,) = res
    b, c, h, w = x.shape
    pad_lo = (local_size - 1) // 2
    pad_hi = local_size - 1 - pad_lo
    geom = _pool_geometry(h, w, tuple(pool_kernel), tuple(pool_stride),
                          tuple(pool_pad))
    kern = functools.partial(
        _fused_tail_bwd_kernel, relu_slope=relu_slope, pad_lo=pad_lo,
        pad_hi=pad_hi, alpha=alpha, beta=beta, k=k, n=local_size, geom=geom)
    dx = _tail_grid_call(
        kern, [x, dy], jax.ShapeDtypeStruct((b, c, h, w), x.dtype),
        interpret)
    return (dx,)


fused_tail_pallas.defvjp(
    lambda x, local_size, alpha, beta, k, relu_slope, pool_kernel,
    pool_stride, pool_pad, interpret:
        _fused_tail_fwd(x, local_size, alpha, beta, k, relu_slope,
                        pool_kernel, pool_stride, pool_pad, interpret),
    _fused_tail_bwd)


def fused_tail_supported(x: jax.Array) -> bool:
    """Same shape/dtype gate as pallas_lrn_supported: the channel axis
    rides the sublanes of the (C, H·W-ish) tile."""
    if x.ndim != 4:
        return False
    sub = 16 if x.dtype == jnp.bfloat16 else 8
    return x.shape[1] % sub == 0 and x.dtype in (jnp.float32, jnp.bfloat16)


def _tail_xla(x, local_size, alpha, beta, k, relu_slope, pool_kernel,
              pool_stride, pool_pad):
    """The exact stock unfused composition (ops.relu → ops.lrn →
    ops.max_pool), so fused-xla nets stay bitwise identical to unfused."""
    if relu_slope is not None:
        x = _relu_op(x, relu_slope)
    x = _lrn_dispatch(x, local_size, alpha, beta, k, "ACROSS_CHANNELS")
    return max_pool(x, tuple(pool_kernel), stride=tuple(pool_stride),
                    pad=tuple(pool_pad))


def fused_conv_lrn_pool(x: jax.Array, w: jax.Array,
                        b: Optional[jax.Array] = None, *,
                        stride: Tuple[int, int] = (1, 1),
                        pad: Tuple[int, int] = (0, 0),
                        dilation: Tuple[int, int] = (1, 1),
                        groups: int = 1,
                        relu_slope: Optional[float] = 0.0,
                        local_size: int = 5, alpha: float = 1.0,
                        beta: float = 0.75, k: float = 1.0,
                        pool_kernel: Tuple[int, int] = (3, 3),
                        pool_stride: Tuple[int, int] = (2, 2),
                        pool_pad: Tuple[int, int] = (0, 0),
                        impl: str = "xla",
                        interpret: Optional[bool] = None) -> jax.Array:
    """One fused tower block: MXU conv + fused relu/LRN/max-pool tail.

    impl='xla' composes the stock ops; impl='pallas' prefers the
    full-block implicit-GEMM kernel (ops/pallas_conv.py: conv on the MXU
    + the whole epilogue in one VMEM residency) where its geometry gate
    passes, degrading to the tail-only kernel and then to the XLA
    composition; impl='pallas-tail' forces the tail-only kernel (the
    full-block A/B control).  Kernels run when the backend is TPU, else
    everything falls back to the XLA composition (interpret=True forces
    the kernels in interpret mode for CPU testing)."""
    if impl in ("pallas", "pallas-tail"):
        run_kernel = (interpret if interpret is not None
                      else jax.default_backend() == "tpu")
        interp = bool(interpret) if interpret is not None else False
        if impl == "pallas" and run_kernel:
            # deferred: pallas_conv imports back into this module
            from . import pallas_conv as _pc

            if _pc.fullblock_supported(x, w, stride=tuple(stride),
                                       pad=tuple(pad),
                                       dilation=tuple(dilation),
                                       groups=groups):
                return _pc.fused_conv_block_pallas(
                    x, w, b, tuple(stride), tuple(pad), groups,
                    relu_slope, local_size, alpha, beta, k,
                    tuple(pool_kernel), tuple(pool_stride),
                    tuple(pool_pad), interp)
        y = conv2d(x, w, b, stride=tuple(stride), pad=tuple(pad),
                   dilation=tuple(dilation), groups=groups)
        if run_kernel and fused_tail_supported(y):
            return fused_tail_pallas(
                y, local_size, alpha, beta, k, relu_slope,
                tuple(pool_kernel), tuple(pool_stride), tuple(pool_pad),
                interp)
    elif impl != "xla":
        raise ValueError(f"fused_conv_lrn_pool impl={impl!r}; "
                         f"expected xla, pallas, or pallas-tail")
    else:
        y = conv2d(x, w, b, stride=tuple(stride), pad=tuple(pad),
                   dilation=tuple(dilation), groups=groups)
    return _tail_xla(y, local_size, alpha, beta, k, relu_slope,
                     pool_kernel, pool_stride, pool_pad)


def fused_out_shape(in_shape: Tuple[int, ...], num_output: int,
                    conv_kernel: Tuple[int, int], conv_pad: Tuple[int, int],
                    conv_stride: Tuple[int, int],
                    conv_dilation: Tuple[int, int],
                    pool_kernel: Tuple[int, int], pool_pad: Tuple[int, int],
                    pool_stride: Tuple[int, int]) -> Tuple[int, ...]:
    """Static (N, C, OH, OW) of the fused block (conv then ceil-mode pool)."""
    from .conv import conv_out_dim

    n, _, h, w = in_shape
    ch = conv_out_dim(h, conv_kernel[0], conv_pad[0], conv_stride[0],
                      conv_dilation[0])
    cw = conv_out_dim(w, conv_kernel[1], conv_pad[1], conv_stride[1],
                      conv_dilation[1])
    oh = pool_out_dim(ch, pool_kernel[0], pool_pad[0], pool_stride[0])
    ow = pool_out_dim(cw, pool_kernel[1], pool_pad[1], pool_stride[1])
    return (n, num_output, oh, ow)
