"""Mixture-of-Experts ops: top-k gating with static capacity, dense MoE FFN.

The reference has no MoE anywhere (SURVEY.md §2.3: expert parallelism absent;
the layer zoo is image-CNN only) — this module exists because the parallelism
inventory (DP/TP/PP/SP/EP) is first-class in the TPU build.  Expert-parallel
execution over a mesh axis lives one level up in parallel/expert.py; here are
the pure single-device ops it is verified against.

Design is GShard/Switch-style (arXiv:2006.16668, 2101.03961) shaped for the
MXU: every tensor is static-shape, token→expert routing is expressed as
one-hot dispatch/combine tensors consumed by einsums (matmuls), and each
expert processes a fixed `capacity` of token slots.  Tokens routed past an
expert's capacity are dropped (their combine weight is zero, so the residual
path — the caller's skip connection — carries them), exactly the standard
capacity-factor semantics.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def expert_capacity(n_tokens: int, n_experts: int, k: int,
                    capacity_factor: float) -> int:
    """Fixed per-expert token slots: ceil(k·T/E · factor), min 1."""
    cap = int(-(-k * n_tokens * capacity_factor // n_experts))
    return max(cap, 1)


def top_k_gating(x: jax.Array, gate_w: jax.Array, *, k: int,
                 capacity: int, return_load_stats: bool = False,
                 ) -> Tuple[jax.Array, jax.Array, Any]:
    """Route (T, M) tokens to the top-k of E experts with static capacity.

    Returns (combine, dispatch, aux_loss):
      combine  (T, E, C) float — gate probability of token t in expert e's
               slot c (zero everywhere the token isn't placed);
      dispatch (T, E, C) float 0/1 — the same placement without the weight;
      aux_loss scalar — Switch load-balancing loss E·Σ_e f_e·p_e (fraction
               of tokens whose TOP-1 is e × mean gate prob of e), which is
               1 at perfect balance.  With return_load_stats=True the third
               element is instead the pair (f, p) so a sharded caller can
               average them across shards BEFORE forming the product (the
               loss is nonlinear in f/p; parallel/expert.py needs this for
               exactness).

    Position-in-expert is assigned in token order per (choice rank, expert)
    via cumsum, the GShard formulation; rank-r choices claim slots after all
    rank-(r-1) choices so top-1 assignments are never bumped by top-2s.
    """
    t, m = x.shape
    e = gate_w.shape[1]
    logits = x @ gate_w                                       # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # top-k expert ids per token, then one-hot masks per choice rank
    _, top_idx = jax.lax.top_k(probs, k)                      # (T, k)
    onehots = jax.nn.one_hot(top_idx, e, dtype=probs.dtype)   # (T, k, E)

    # aux loss uses rank-0 assignment (Switch: arXiv:2101.03961 eq. 4-6)
    f = jnp.mean(onehots[:, 0, :], axis=0)                    # (E,)
    p = jnp.mean(probs, axis=0)                               # (E,)
    aux_loss = e * jnp.sum(f * p)

    # slot assignment: flatten choices rank-major so cumsum gives rank-0
    # choices of ALL tokens positions before any rank-1 choice
    flat = jnp.transpose(onehots, (1, 0, 2)).reshape(k * t, e)
    pos = jnp.cumsum(flat, axis=0) - flat                     # (k·T, E)
    keep = flat * (pos < capacity)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=probs.dtype) * keep[..., None]
    # back to (T, k, E, C), sum over choice rank (a token can't pick the
    # same expert twice via top_k, so the sum is still one-hot)
    dispatch = jnp.sum(slot.reshape(k, t, e, capacity), axis=0)

    # combine weight = raw softmax prob of the chosen expert (Switch-style;
    # un-renormalized so a dropped top-1 doesn't inflate the top-2's share)
    combine = dispatch * probs[:, :, None]                    # (T, E, C)
    if return_load_stats:
        return combine, dispatch, (f, p)
    return combine, dispatch, aux_loss


def moe_ffn(x: jax.Array, gate_w: jax.Array, w1: jax.Array, b1: jax.Array,
            w2: jax.Array, b2: jax.Array, *, k: int = 1,
            capacity_factor: float = 1.25,
            ) -> Tuple[jax.Array, jax.Array]:
    """Dense (single-device) MoE feed-forward: (…, M) -> (…, M).

    gate_w (M, E); w1 (E, M, H), b1 (E, H), w2 (E, H, M), b2 (E, M).
    Leading axes flatten to a token axis.  Returns (y, aux_loss).  Dropped
    tokens yield zeros — callers add the residual/skip path.
    """
    lead = x.shape[:-1]
    m = x.shape[-1]
    xt = x.reshape(-1, m)
    t = xt.shape[0]
    e = gate_w.shape[1]
    cap = expert_capacity(t, e, k, capacity_factor)
    combine, dispatch, aux = top_k_gating(xt, gate_w, k=k, capacity=cap)
    # dispatch tokens into expert slot buffers: (E, C, M)
    buf = jnp.einsum("tec,tm->ecm", dispatch, xt)
    h = jax.nn.relu(jnp.einsum("ecm,emh->ech", buf, w1) + b1[:, None, :])
    out = jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]
    # only filled slots may contribute (empty slots still got b2)
    out = out * jnp.sum(dispatch, axis=0)[..., None]
    y = jnp.einsum("tec,ecm->tm", combine, out)
    return y.reshape(*lead, m), aux
