"""Loss and metric ops (reference: caffe/src/caffe/layers/*loss*.cpp,
accuracy_layer.cpp).  All return scalars with the reference's exact
normalization so loss curves and epochs-to-accuracy are comparable.

Label blobs are integer class ids shaped (N,) or (N, 1, H, W) — spatial
(inner) label dims are supported the way the reference's outer/inner split is
(softmax_loss_layer.cpp:40-60).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def softmax(x: jax.Array, axis: int = 1) -> jax.Array:
    return jax.nn.softmax(x, axis=axis)


def _flatten_outer_inner(scores: jax.Array, labels: jax.Array, axis: int):
    """(outer, C, inner) view of scores + (outer, inner) labels."""
    c = scores.shape[axis]
    outer = 1
    for s in scores.shape[:axis]:
        outer *= s
    inner = 1
    for s in scores.shape[axis + 1:]:
        inner *= s
    s3 = scores.reshape(outer, c, inner)
    l2 = labels.reshape(outer, inner).astype(jnp.int32)
    return s3, l2, outer, inner, c


def softmax_with_loss(scores: jax.Array, labels: jax.Array, *, axis: int = 1,
                      ignore_label: Optional[int] = None,
                      normalize: bool = True) -> jax.Array:
    """reference: softmax_loss_layer.cpp:55-83 (forward), :85-118 (normalizer:
    non-ignored count when normalize else outer_num)."""
    s3, l2, outer, inner, c = _flatten_outer_inner(scores, labels, axis)
    # loss math in >= fp32: under bf16 mixed precision log_softmax over 1000
    # classes loses too much, so upcast — but never DOWNcast (the float64
    # validation harness runs the whole step at f64)
    if s3.dtype not in (jnp.float32, jnp.float64):
        s3 = s3.astype(jnp.float32)
    logp = jax.nn.log_softmax(s3, axis=1)
    picked = jnp.take_along_axis(logp, l2[:, None, :], axis=1)[:, 0, :]
    if ignore_label is not None:
        valid = (l2 != ignore_label)
        picked = jnp.where(valid, picked, 0.0)
        count = jnp.sum(valid)
    else:
        count = outer * inner
    total = -jnp.sum(picked)
    if normalize:
        return total / jnp.maximum(count, 1)
    return total / outer


def multinomial_logistic_loss(prob: jax.Array, labels: jax.Array,
                              ) -> jax.Array:
    """Input is already a probability distribution
    (reference: multinomial_logistic_loss_layer.cpp:27-41)."""
    n = prob.shape[0]
    l = labels.reshape(n).astype(jnp.int32)
    p = prob.reshape(n, -1)
    picked = jnp.take_along_axis(p, l[:, None], axis=1)[:, 0]
    return -jnp.sum(jnp.log(jnp.maximum(picked, 1e-20))) / n


def infogain_loss(prob: jax.Array, labels: jax.Array, H: jax.Array,
                  ) -> jax.Array:
    """loss = -sum_j H[label, j] log(p_j) / num
    (reference: infogain_loss_layer.cpp:59-76)."""
    n = prob.shape[0]
    l = labels.reshape(n).astype(jnp.int32)
    p = prob.reshape(n, -1)
    rows = H[l]  # (n, dim)
    return -jnp.sum(rows * jnp.log(jnp.maximum(p, 1e-20))) / n


def euclidean_loss(a: jax.Array, b: jax.Array) -> jax.Array:
    """loss = ||a-b||^2 / (2N) (reference: euclidean_loss_layer.cpp:21-32)."""
    n = a.shape[0]
    d = (a - b).reshape(n, -1)
    return jnp.sum(d * d) / (2.0 * n)


def sigmoid_cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                               ) -> jax.Array:
    """Stable BCE-with-logits, normalized by batch num
    (reference: sigmoid_cross_entropy_loss_layer.cpp:34-52)."""
    n = logits.shape[0]
    x = logits
    z = targets
    per = jnp.maximum(x, 0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return jnp.sum(per) / n


def hinge_loss(scores: jax.Array, labels: jax.Array, *, norm: str = "L1",
               ) -> jax.Array:
    """reference: hinge_loss_layer.cpp:10-41 — margins include the label
    column (contributing max(0, 1 - s_label))."""
    n = scores.shape[0]
    s = scores.reshape(n, -1)
    l = labels.reshape(n).astype(jnp.int32)
    signs = jnp.ones_like(s).at[jnp.arange(n), l].set(-1.0)
    margins = jnp.maximum(0.0, 1.0 + signs * s)
    if norm == "L2":
        return jnp.sum(margins * margins) / n
    return jnp.sum(margins) / n


def contrastive_loss(a: jax.Array, b: jax.Array, y: jax.Array, *,
                     margin: float = 1.0, legacy_version: bool = False,
                     ) -> jax.Array:
    """reference: contrastive_loss_layer.cpp:28-59 — y=1 similar pairs pull
    (d^2), y=0 dissimilar push (max(margin - d, 0)^2, or legacy margin - d^2)."""
    n = a.shape[0]
    diff = (a - b).reshape(n, -1)
    d2 = jnp.sum(diff * diff, axis=1)
    ysim = y.reshape(n).astype(a.dtype)
    if legacy_version:
        push = jnp.maximum(margin - d2, 0.0)
    else:
        d = jnp.sqrt(jnp.maximum(d2, 1e-12))
        push = jnp.square(jnp.maximum(margin - d, 0.0))
    per = ysim * d2 + (1.0 - ysim) * push
    return jnp.sum(per) / (2.0 * n)


def accuracy(scores: jax.Array, labels: jax.Array, *, top_k: int = 1,
             axis: int = 1, ignore_label: Optional[int] = None) -> jax.Array:
    """Fraction of (non-ignored) positions whose label is in the top-k
    (reference: accuracy_layer.cpp:37-74)."""
    s3, l2, outer, inner, c = _flatten_outer_inner(scores, labels, axis)
    # rank of the true-label score; ties break toward the larger class id,
    # matching the reference's partial_sort over (score, id) pairs
    # (accuracy_layer.cpp:57-66)
    true_scores = jnp.take_along_axis(s3, l2[:, None, :], axis=1)
    cls = jnp.arange(c).reshape(1, c, 1)
    higher = jnp.sum(s3 > true_scores, axis=1) + jnp.sum(
        (s3 == true_scores) & (cls > l2[:, None, :]), axis=1)
    hit = (higher < top_k)
    if ignore_label is not None:
        valid = (l2 != ignore_label)
        correct = jnp.sum(jnp.where(valid, hit, False))
        count = jnp.maximum(jnp.sum(valid), 1)
    else:
        correct = jnp.sum(hit)
        count = outer * inner
    return correct.astype(jnp.float32) / count


def argmax(x: jax.Array, *, top_k: int = 1, out_max_val: bool = False,
           axis: Optional[int] = None) -> jax.Array:
    """reference: argmax_layer.cpp:28-74."""
    if axis is not None:
        if top_k == 1:
            idx = jnp.argmax(x, axis=axis, keepdims=True)
            if out_max_val:
                return jnp.max(x, axis=axis, keepdims=True)
            return idx.astype(x.dtype)
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), top_k)
        out = vals if out_max_val else idx.astype(x.dtype)
        return jnp.moveaxis(out, -1, axis)
    n = x.shape[0]
    flat = x.reshape(n, -1)
    vals, idx = jax.lax.top_k(flat, top_k)
    if out_max_val:
        # (N, 2, top_k): indices then values (argmax_layer.cpp:58-66)
        return jnp.stack([idx.astype(x.dtype), vals], axis=1)
    return idx.astype(x.dtype).reshape(n, 1, top_k)
