"""Normalization layers: BatchNorm and MVN.

This Caffe vintage's BatchNorm has NO learnable scale/shift — its three blobs
are (running_mean, running_var, moving_average_scale) and affine transforms
are done by a separate layer (reference: caffe/src/caffe/layers/
batch_norm_layer.cpp:7-48; blob layout :27-36).  We keep that contract: the
learnable-params list carries the same three blobs, updated functionally.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def batch_norm(x: jax.Array, mean_blob: jax.Array, var_blob: jax.Array,
               scale_blob: jax.Array, *, use_global_stats: bool,
               eps: float = 1e-5, moving_average_fraction: float = 0.999,
               ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array, jax.Array]]:
    """Returns (y, updated_stat_blobs).

    Training (use_global_stats=False): normalize by batch statistics over
    (N, H, W) and fold them into the running blobs the way the reference does
    (stored blobs are *unscaled* accumulations; divide by scale_blob on use,
    batch_norm_layer.cpp:59-78).  Inference: use stored stats.
    """
    c = x.shape[1]
    axes = (0,) + tuple(range(2, x.ndim))
    if use_global_stats:
        scale = jnp.where(scale_blob == 0, 1.0, scale_blob)
        mean = mean_blob / scale
        var = var_blob / scale
        new_blobs = (mean_blob, var_blob, scale_blob)
    else:
        mean = jnp.mean(x, axis=axes)
        var = jnp.mean(jnp.square(x), axis=axes) - jnp.square(mean)
        m = 1
        for a in axes:
            m *= x.shape[a]
        bias_corr = m / max(m - 1, 1)
        new_scale = scale_blob * moving_average_fraction + 1.0
        new_mean = mean_blob * moving_average_fraction + mean
        new_var = var_blob * moving_average_fraction + bias_corr * var
        new_blobs = (new_mean, new_var, new_scale)
    shape = (1, c) + (1,) * (x.ndim - 2)
    y = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
    return y, new_blobs


def mvn(x: jax.Array, *, normalize_variance: bool = True,
        across_channels: bool = False, eps: float = 1e-9) -> jax.Array:
    """Mean-variance normalization per sample
    (reference: caffe/src/caffe/layers/mvn_layer.cpp:37-78)."""
    if across_channels:
        axes = tuple(range(1, x.ndim))
    else:
        axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    y = x - mean
    if normalize_variance:
        # reference computes E[x^2] - E[x]^2 then uses std + eps in the divisor
        var = jnp.mean(jnp.square(x), axis=axes, keepdims=True) - jnp.square(mean)
        y = y / (jnp.sqrt(var) + eps)
    return y


def scale_shift(x: jax.Array, scale: jax.Array,
                bias: Optional[jax.Array] = None, *, axis: int = 1,
                ) -> jax.Array:
    """Channelwise affine (the companion `Scale` layer pattern; this vintage
    pairs BatchNorm with it in BN prototxts like cifar10_full_sigmoid_bn —
    reference: caffe/examples/cifar10/cifar10_full_sigmoid_train_test_bn.prototxt)."""
    nd = x.ndim
    shape = [1] * nd
    for i, s in enumerate(scale.shape):
        shape[axis + i] = s
    y = x * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return y
