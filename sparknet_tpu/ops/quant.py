"""Post-training quantization primitives: per-channel symmetric int8.

Weight-only quantization (w8a16): weights are stored int8 with one f32
scale per output channel (axis 0 of every Caffe-layout weight —
conv OIHW rows and inner-product (out, in) rows are both independent
dot products, so per-row scaling is exact per-channel), then
dequantized to the compute dtype INSIDE the jitted forward.  Symmetric
(zero-point-free) quantization keeps the dequant a single multiply;
127 (not 128) bounds the grid so +/- ranges stay symmetric.

The serving integration (calibration, param-tree plumbing, mode
selection) lives in serving/quant.py; these are the pure-math pieces.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

INT8_LEVELS = 127  # symmetric: q in [-127, 127], -128 unused


def quantize_per_channel_int8(w: jax.Array, axis: int = 0,
                              ) -> Tuple[jax.Array, jax.Array]:
    """w -> (q int8, scale f32) with one scale per slice along `axis`.

    scale = max|w| / 127 per channel (1.0 for all-zero channels, so the
    dequant stays finite and exact); q = round(w / scale) clipped to
    [-127, 127].  Round-trip error is bounded by scale/2 per element.
    """
    w = w.astype(jnp.float32)
    reduce_axes = tuple(d for d in range(w.ndim) if d != axis)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes)
    scale = jnp.where(amax > 0, amax / INT8_LEVELS, 1.0)
    bshape = tuple(w.shape[axis] if d == axis else 1 for d in range(w.ndim))
    q = jnp.clip(jnp.round(w / scale.reshape(bshape)),
                 -INT8_LEVELS, INT8_LEVELS).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_int8(q: jax.Array, scale: jax.Array, axis: int = 0,
                    dtype=jnp.bfloat16) -> jax.Array:
    """(q, scale) -> w in `dtype`.  The multiply runs in f32 (int8
    magnitudes are exact in f32; a bf16 multiply would round the scale
    AND the product) and casts once at the end."""
    bshape = tuple(q.shape[axis] if d == axis else 1 for d in range(q.ndim))
    return (q.astype(jnp.float32) * scale.reshape(bshape)).astype(dtype)


def top1_agreement(probs_a: jax.Array, probs_b: jax.Array) -> float:
    """Fraction of rows where the two (N, K) score matrices agree on the
    argmax — the calibration metric for post-training quantization."""
    a = jnp.argmax(jnp.asarray(probs_a), axis=-1)
    b = jnp.argmax(jnp.asarray(probs_b), axis=-1)
    return float(jnp.mean((a == b).astype(jnp.float32)))
