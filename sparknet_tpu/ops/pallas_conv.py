"""Full-block Pallas tower kernel: implicit-GEMM conv + fused epilogue.

ops/fused_block.py fuses only the memory-bound TAIL of the AlexNet
norm1/norm2 tower stages (relu → LRN → pool) and leaves the conv — the
dominant FLOP sink — to stock XLA, so the conv output still makes one
full HBM round-trip before the fused tail reads it back.  The PHAST
Caffe-port lesson (PAPERS.md, arXiv:2005.13076) is that kernel-by-kernel
translation leaves exactly this win on the table; Caffe itself
(arXiv:1408.5093) collapsed the tower into one tight kernel.  This
module closes the gap: ONE Pallas kernel per batch element computes the
convolution as an implicit GEMM on the MXU and runs the whole
bias → [ReLU] → LRN(ACROSS_CHANNELS) → ceil-mode MAX-pool epilogue in
the same VMEM residency, writing only the pooled output to HBM.

The conv keeps the MXU (the fused_block.py docstring's own warning: a
hand-written VPU conv forfeits the systolic array):

  * the (C, H, W) plane is zero-padded and stride-reshaped once, and the
    kh·kw window offsets become UNIT-stride slices of the reshaped map —
    the same Mosaic-safe reshape trick fused_block.py uses for the pool
    (offset i ↦ r[:, di:di+oh, ri, ...] with (di, ri) = divmod(i, sh));
  * stacking those slices yields the im2col matrix (C·kh·kw, oh·ow)
    WITHOUT an HBM materialization — it exists only in VMEM;
  * each filter group is one `jnp.dot` on the MXU with
    preferred_element_type=float32, so bf16 inputs accumulate in fp32
    (the mixed-precision contract: bf16 multiplicands, fp32 partials).

The col-matrix row order is c·(kh·kw) + i·kw + j — the OIHW weight
blob's own minor order — so `w.reshape(O, -1)` lines up with no
in-kernel weight shuffle.

Epilogue math is IDENTICAL to fused_block's tail kernel (the helpers are
imported, not re-derived), so full-block and tail-only forwards agree
bit-for-bit and the backward can reuse the tail kernel: the custom VJP
recomputes the conv output (one XLA conv — cheaper than writing the
pre-pool activation through HBM, the pallas_lrn measured lesson), routes
dy through fused_tail_pallas's fused backward kernel, and closes with
XLA's conv transpose for dx/dw/db.

Dispatch (ops/fused_block.fused_conv_lrn_pool): SPARKNET_FUSED_BLOCKS=
pallas prefers this kernel where `fullblock_supported` passes (AlexNet
norm1/norm2; GoogLeNet's conv2 stage at bf16) and falls back to the
tail-only kernel, then to the XLA composition; `pallas-tail` forces the
tail-only kernel (the A/B control scripts/fullblock_probe.py drives).
jax.experimental.pallas is imported only inside the grid call, keeping
the portable path pallas-free (the ops.lrn deferred-import contract).

Reference semantics: caffe conv_layer.cpp output dims (floor mode),
lrn_layer.cpp:88-119 forward, pooling_layer.cpp:155-169 max routing.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .conv import conv2d, conv_out_dim
from .fused_block import (_apply_relu, _pool_geometry, _pool_patches,
                          _PoolGeom, _winsum_c, fused_tail_pallas)
from .lrn import _powm

# VMEM footprint ceiling for the gate: the in-VMEM col matrix is the
# big term (C·kh·kw·oh·ow), and 12 MB leaves headroom under the ~16 MB
# core budget for Mosaic's own double-buffering.  AlexNet conv1/conv2
# fit at fp32; GoogLeNet's conv2 stage (64ch 56² k3 → 192) fits at bf16
# only — exactly the precision bench.py trains at.
_VMEM_BUDGET = 12 * 2 ** 20


def _conv_geometry(h: int, w: int, kernel: Tuple[int, int],
                   stride: Tuple[int, int],
                   pad: Tuple[int, int]) -> _PoolGeom:
    """Reshape-trick geometry for the conv's window slices — the
    fused_block._pool_geometry construction with FLOOR-mode output dims
    (conv_layer.cpp) instead of ceil-mode pooling ones."""
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    oh = conv_out_dim(h, kh, ph, sh)
    ow = conv_out_dim(w, kw, pw, sw)
    need_h = max((oh - 1) * sh + kh, h + ph)
    need_w = max((ow - 1) * sw + kw, w + pw)
    hp = -(-need_h // sh) * sh
    wp = -(-need_w // sw) * sw
    return _PoolGeom(h, w, kh, kw, sh, sw, oh, ow, ph, pw, hp, wp,
                     hp // sh, wp // sw)


def _im2col_vmem(x: jax.Array, cg: _PoolGeom) -> jax.Array:
    """(C, H, W) → the (C·kh·kw, oh·ow) col matrix, all unit-stride
    slices (zero padding: conv semantics, not the pool's -inf)."""
    c = x.shape[0]
    xp = jnp.pad(x, ((0, 0),
                     (cg.pad_h_lo, cg.hp - cg.h - cg.pad_h_lo),
                     (cg.pad_w_lo, cg.wp - cg.w - cg.pad_w_lo)))
    r = xp.reshape(c, cg.lh, cg.sh, cg.lw, cg.sw)
    patches = []
    for i in range(cg.kh):
        di, ri = divmod(i, cg.sh)
        for j in range(cg.kw):
            dj, rj = divmod(j, cg.sw)
            patches.append(r[:, di:di + cg.oh, ri, dj:dj + cg.ow, rj])
    # stack on axis 1: row index c·(kh·kw) + i·kw + j, the OIHW minor
    # order, so w.reshape(O, -1) needs no in-kernel shuffle
    return jnp.stack(patches, axis=1).reshape(
        c * cg.kh * cg.kw, cg.oh * cg.ow)


def _fullblock_kernel(*refs, cg, pg, groups, relu_slope, pad_lo, pad_hi,
                      alpha, beta, k, n):
    if len(refs) == 4:
        x_ref, w_ref, b_ref, y_ref = refs
    else:
        (x_ref, w_ref, y_ref), b_ref = refs, None
    x = x_ref[0]
    w = w_ref[...]
    o = w.shape[0]
    cols = _im2col_vmem(x, cg)
    og = o // groups
    rows = cols.shape[0] // groups
    outs = []
    for g in range(groups):
        wg = w[g * og:(g + 1) * og].reshape(og, rows)
        outs.append(jnp.dot(wg, cols[g * rows:(g + 1) * rows],
                            preferred_element_type=jnp.float32))
    y = (outs[0] if groups == 1
         else jnp.concatenate(outs, axis=0)).reshape(o, cg.oh, cg.ow)
    if b_ref is not None:
        y = y + b_ref[...].astype(jnp.float32).reshape(o, 1, 1)
    # epilogue: the EXACT fused_block tail formulation (same helpers),
    # so full-block and tail-only forwards agree bit-for-bit
    xr = _apply_relu(y, relu_slope)
    scale = k + (alpha / n) * _winsum_c(xr * xr, pad_lo, pad_hi)
    z = xr * _powm(scale, -beta)
    pooled = _pool_patches(z, pg)
    acc = pooled[0]
    for p in pooled[1:]:
        acc = jnp.maximum(acc, p)
    y_ref[0] = acc.astype(y_ref.dtype)


def _fullblock_grid_call(kernel, x, w, b, out_shape, interpret: bool):
    # deferred: keeps jax.experimental.pallas off the module-import path
    # (the ops.lrn dispatch contract, pinned by test_pallas_conv.py)
    from jax.experimental import pallas as pl

    bsz = x.shape[0]
    in_specs = [pl.BlockSpec((1,) + tuple(x.shape[1:]),
                             lambda i: (i, 0, 0, 0)),
                pl.BlockSpec(tuple(w.shape), lambda i: (0, 0, 0, 0))]
    inputs = [x, w]
    if b is not None:
        in_specs.append(pl.BlockSpec((int(b.shape[0]), 1),
                                     lambda i: (0, 0)))
        inputs.append(b.reshape(-1, 1))
    out_spec = pl.BlockSpec((1,) + tuple(out_shape.shape[1:]),
                            lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(bsz,),
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)


# nondiff: (stride, pad, groups, relu_slope, local_size, alpha, beta, k,
#           pool_kernel, pool_stride, pool_pad, interpret)
@functools.partial(jax.custom_vjp, nondiff_argnums=tuple(range(3, 15)))
def fused_conv_block_pallas(x: jax.Array, w: jax.Array,
                            b: Optional[jax.Array],
                            stride: Tuple[int, int],
                            pad: Tuple[int, int], groups: int,
                            relu_slope: Optional[float], local_size: int,
                            alpha: float, beta: float, k: float,
                            pool_kernel: Tuple[int, int],
                            pool_stride: Tuple[int, int],
                            pool_pad: Tuple[int, int],
                            interpret: bool = False) -> jax.Array:
    """The whole tower block — conv (implicit GEMM, MXU, fp32 accum) +
    bias + [relu] + LRN(ACROSS) + ceil-mode MAX-pool — as ONE kernel.

    x is (N, C, H, W), w is OIHW, b is (O,) or None; relu_slope=None
    skips the relu stage.  Returns (N, O, pool_oh, pool_ow) in x.dtype."""
    y, _ = _fullblock_fwd(x, w, b, stride, pad, groups, relu_slope,
                          local_size, alpha, beta, k, pool_kernel,
                          pool_stride, pool_pad, interpret)
    return y


def _fullblock_fwd(x, w, b, stride, pad, groups, relu_slope, local_size,
                   alpha, beta, k, pool_kernel, pool_stride, pool_pad,
                   interpret):
    bsz, _, h, wd = x.shape
    o, _, kh, kw = w.shape
    cg = _conv_geometry(h, wd, (kh, kw), tuple(stride), tuple(pad))
    pg = _pool_geometry(cg.oh, cg.ow, tuple(pool_kernel),
                        tuple(pool_stride), tuple(pool_pad))
    pad_lo = (local_size - 1) // 2
    pad_hi = local_size - 1 - pad_lo
    kern = functools.partial(
        _fullblock_kernel, cg=cg, pg=pg, groups=groups,
        relu_slope=relu_slope, pad_lo=pad_lo, pad_hi=pad_hi, alpha=alpha,
        beta=beta, k=k, n=local_size)
    out = jax.ShapeDtypeStruct((bsz, o, pg.oh, pg.ow), x.dtype)
    y = _fullblock_grid_call(kern, x, w, b, out, interpret)
    return y, (x, w, b)


def _fullblock_bwd(stride, pad, groups, relu_slope, local_size, alpha,
                   beta, k, pool_kernel, pool_stride, pool_pad, interpret,
                   res, dy):
    # recompute the conv output rather than saving it: one XLA conv beats
    # writing the full pre-pool activation through HBM (the pallas_lrn
    # measured lesson); the tail gradient then reuses fused_block's fused
    # backward kernel, and XLA's conv transpose closes dx/dw/db
    x, w, b = res

    def conv(x_, w_, b_):
        return conv2d(x_, w_, b_, stride=tuple(stride), pad=tuple(pad),
                      groups=groups)

    y_conv, conv_vjp = jax.vjp(conv, x, w, b)
    _, tail_vjp = jax.vjp(
        lambda y_: fused_tail_pallas(y_, local_size, alpha, beta, k,
                                     relu_slope, tuple(pool_kernel),
                                     tuple(pool_stride), tuple(pool_pad),
                                     interpret), y_conv)
    (dconv,) = tail_vjp(dy)
    return conv_vjp(dconv)


fused_conv_block_pallas.defvjp(
    lambda x, w, b, stride, pad, groups, relu_slope, local_size, alpha,
    beta, k, pool_kernel, pool_stride, pool_pad, interpret:
        _fullblock_fwd(x, w, b, stride, pad, groups, relu_slope,
                       local_size, alpha, beta, k, pool_kernel,
                       pool_stride, pool_pad, interpret),
    _fullblock_bwd)


def _vmem_estimate(in_shape, w_shape, cg: _PoolGeom, dtype) -> int:
    """Rough per-grid-cell VMEM bytes: padded input plane + in-VMEM col
    matrix + weights (input dtype) + two fp32 activation-sized buffers
    for the epilogue chain (conservative: Mosaic fuses most of it)."""
    _, c, _, _ = in_shape
    o = w_shape[0]
    itm = 2 if dtype == jnp.bfloat16 else 4
    return (c * cg.hp * cg.wp * itm
            + c * cg.kh * cg.kw * cg.oh * cg.ow * itm
            + o * w_shape[1] * cg.kh * cg.kw * itm
            + 2 * o * cg.oh * cg.ow * 4)


def fullblock_geometry_supported(in_shape: Tuple[int, ...],
                                 w_shape: Tuple[int, ...], *,
                                 stride: Tuple[int, int],
                                 pad: Tuple[int, int],
                                 dilation: Tuple[int, int] = (1, 1),
                                 groups: int = 1,
                                 dtype=jnp.float32) -> bool:
    """Static gate for the full-block kernel: NCHW f32/bf16 input, unit
    dilation (the reshape trick has no dilated form), output channels on
    a whole sublane tile (the epilogue/backward ride the tail kernel's
    layout, fused_tail_supported's condition), and the per-cell VMEM
    estimate under _VMEM_BUDGET."""
    if len(in_shape) != 4 or len(w_shape) != 4:
        return False
    if tuple(dilation) != (1, 1):
        return False
    dtype = jnp.dtype(dtype)
    if dtype not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return False
    o = w_shape[0]
    sub = 16 if dtype == jnp.bfloat16 else 8
    if o % sub != 0 or o % groups != 0 or in_shape[1] % groups != 0:
        return False
    _, _, h, wd = in_shape
    kh, kw = int(w_shape[2]), int(w_shape[3])
    if h + 2 * pad[0] < kh or wd + 2 * pad[1] < kw:
        return False
    cg = _conv_geometry(h, wd, (kh, kw), tuple(stride), tuple(pad))
    return _vmem_estimate(in_shape, w_shape, cg, dtype) <= _VMEM_BUDGET


def fullblock_supported(x: jax.Array, w: jax.Array, *,
                        stride: Tuple[int, int], pad: Tuple[int, int],
                        dilation: Tuple[int, int] = (1, 1),
                        groups: int = 1) -> bool:
    """Runtime gate: geometry + matching input/weight dtype."""
    return (x.dtype == w.dtype
            and fullblock_geometry_supported(
                tuple(x.shape), tuple(w.shape), stride=tuple(stride),
                pad=tuple(pad), dilation=tuple(dilation), groups=groups,
                dtype=x.dtype))
