"""Device-side data augmentation: the DataTransformer pipeline (random/center
crop, mirror, mean subtract, scale — reference:
caffe/src/caffe/data_transformer.cpp) as a jittable function over uint8
batches.

The reference transforms on the host because 2015 Caffe fed GPUs from CPU
loops; on TPU the right split is different: the host ships the RAW uint8
bytes (4x less host->device bandwidth than float32 — usually the feed
bottleneck) and the crop/mirror/mean/scale arithmetic fuses into the
compiled train step, where it is effectively free next to the conv FLOPs.
Semantics match DataTransformer: per-image random crop offsets and mirror
draws in TRAIN phase, center crop and no mirror in TEST, mean image indexed
at the crop window (data_transformer.cpp:Transform).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def make_device_transformer(*, crop_size: int = 0, mirror: bool = False,
                            mean_image: Optional[np.ndarray] = None,
                            mean_values=(), scale: float = 1.0,
                            phase: str = "TRAIN"):
    """Returns fn(batch_u8_or_float, rng) -> float32 (N, C, crop, crop).

    Compose it with a training step under one jit so XLA fuses the
    subtract/scale into the first conv's input pipeline."""
    mean_arr = None
    if mean_image is not None:
        mean_arr = jnp.asarray(np.asarray(mean_image, np.float32))
    mv = jnp.asarray(np.asarray(mean_values, np.float32)) \
        if len(mean_values) else None
    train = phase == "TRAIN"

    def transform(x, rng):
        x = x.astype(jnp.float32)
        n, c, h, w = x.shape
        if mean_arr is not None:
            x = x - mean_arr  # full-size mean: crop window then aligns
        elif mv is not None:
            x = x - mv[None, :, None, None]
        cs = crop_size
        if cs and (h > cs or w > cs):
            if train:
                kh, kw = jax.random.split(rng, 2)
                oh = jax.random.randint(kh, (n,), 0, h - cs + 1)
                ow = jax.random.randint(kw, (n,), 0, w - cs + 1)
            else:
                oh = jnp.full((n,), (h - cs) // 2)
                ow = jnp.full((n,), (w - cs) // 2)

            def crop_one(img, r0, c0):
                return jax.lax.dynamic_slice(img, (0, r0, c0), (c, cs, cs))

            x = jax.vmap(crop_one)(x, oh, ow)
        if mirror and train:
            flip = jax.random.bernoulli(jax.random.fold_in(rng, 7), 0.5,
                                        (n,))
            x = jnp.where(flip[:, None, None, None], x[:, :, :, ::-1], x)
        if scale != 1.0:
            x = x * scale
        return x

    return transform


def fuse_transform_into_step(transform, step):
    """(params, state, it, {"data": u8, "label": l}, rng) -> step on the
    transformed batch — one compiled program, raw bytes over the wire."""

    def fused(params, state, it, inputs, rng):
        data = transform(inputs["data"], jax.random.fold_in(rng, 13))
        return step(params, state, it,
                    {**inputs, "data": data}, rng)

    return fused
