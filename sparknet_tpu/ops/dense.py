"""Fully-connected and embedding ops.

Weight layouts match the reference blobs so weight interchange and per-blob
lr_mult carry over: InnerProduct weight is (num_output, fan_in)
(reference: caffe/src/caffe/layers/inner_product_layer.cpp:28-45), Embed
weight is (input_dim, num_output) (embed_layer.cpp:20-35).  The matmuls are
the MXU hot path — keep them batched and let XLA tile them.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _prod(shape) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out


def inner_product(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None,
                  *, axis: int = 1) -> jax.Array:
    """y = flatten(x, from=axis) @ w.T + b.

    Axes before `axis` are batch dims; trailing axes fold into the fan-in
    (reference: inner_product_layer.cpp:46-60)."""
    lead = x.shape[:axis]
    xf = x.reshape((_prod(lead), -1))
    y = xf @ w.T
    if b is not None:
        y = y + b
    return y.reshape(lead + (w.shape[0],))


def embed(indices: jax.Array, w: jax.Array, b: Optional[jax.Array] = None,
          ) -> jax.Array:
    """Lookup rows of w by integer index (reference: embed_layer.cpp:40-55)."""
    idx = indices.astype(jnp.int32)
    y = w[idx]
    if b is not None:
        y = y + b
    return y
