"""Attention ops: standard, and blockwise-streaming (online softmax).

The reference has no attention anywhere (SURVEY.md §5.7: image CNNs only;
RNNs were future work) — this module exists because long-context support is
first-class in the TPU build.  The blockwise form is the building block of
ring attention (parallel/ring_attention.py): it never materializes the full
(S, S) score matrix, trading HBM for recompute exactly the way flash
attention does, and XLA fuses each block's matmul chain onto the MXU.

Shapes: (batch, heads, seq, head_dim) throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = False, scale: Optional[float] = None,
              q_offset: int = 0, k_offset: int = 0) -> jax.Array:
    """Reference (dense) softmax attention; offsets give global positions for
    causal masking of sequence shards.  Fully-masked query rows (possible
    when a key shard lies entirely in a query shard's future) produce zeros,
    not a uniform average."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(q.shape[2]) + q_offset
        kpos = jnp.arange(k.shape[2]) + k_offset
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    m = scores.max(axis=-1, keepdims=True)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(scores - m_safe)  # masked entries underflow to exactly 0
    denom = p.sum(axis=-1, keepdims=True)
    p = p / jnp.maximum(denom, 1e-30)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _block_update(carry, q, k, v, scale, mask):
    """One online-softmax accumulation step (the flash-attention recurrence).

    Robust to fully-masked blocks: while a row has seen no valid key, m stays
    at NEG_INF and (corr, p) are arranged so l remains exactly 0 — the caller
    can then map l == 0 rows to zero output."""
    o, m, l = carry
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # exp(-1e30 - -1e30) would be 1 and pollute l; subtract a zeroed max for
    # still-all-masked rows so every masked p underflows to 0 instead
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_safe[..., None])
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return (o_new, m_new, l_new)


def flash_attention_tpu(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Flash attention: the fused Pallas kernel jax ships
    (jax.experimental.pallas.ops.tpu.flash_attention) when explicitly
    enabled AND proven compilable, else `blockwise_attention` — the same
    online-softmax recurrence through XLA, asserted equivalent in
    tests/test_attention.py.

    The Pallas kernel is OPT-IN via SPARKNET_FLASH_ATTENTION=1 rather than
    auto-selected on TPU: on some platforms (this project's tunneled dev
    TPU among them) the shipped kernel HANGS at compile — not an exception
    a fallback could catch.  Even with the flag set, the kernel is only
    used after `flash_probe.probe_flash_kernel` compiles it in a child
    process under a hard timeout (verdict cached), so this call can never
    hang the host process.  Once the probe has passed, a failure from the
    real kernel is a genuine bug and PROPAGATES — the user explicitly
    asked for this kernel; silently degrading to a slower path would hide
    the failure (ADVICE r2)."""
    import os

    if scale is None:
        scale = q.shape[-1] ** -0.5
    if os.environ.get("SPARKNET_FLASH_ATTENTION") == "1":
        reason = None
        if jax.devices()[0].platform != "tpu":
            reason = "flash kernel is TPU-only"
        else:
            from .flash_probe import probe_flash_kernel

            if not probe_flash_kernel():
                reason = ("subprocess compile probe failed or timed out "
                          "(verdict cached; flash_probe.clear_probe_cache"
                          "() to re-probe)")
        if reason is None:
            from jax.experimental.pallas.ops.tpu.flash_attention import \
                flash_attention

            try:
                return flash_attention(q, k, v, causal=causal,
                                       sm_scale=scale)
            except (NotImplementedError, ValueError, TypeError) as e:
                # the kernel REJECTED these inputs (block-divisibility,
                # unsupported dtype/shape) — the probe's canonical shape
                # can't anticipate every model's shapes, so rejection
                # falls back like the pre-probe path did.  Anything else
                # (runtime failure, OOM) propagates: the user explicitly
                # asked for this kernel and the probe proved it works
                # (ADVICE r2).
                reason = f"kernel rejected inputs: {e}"
        import warnings

        warnings.warn(f"SPARKNET_FLASH_ATTENTION=1 but the pallas "
                      f"kernel was not used ({reason}); falling back to "
                      f"blockwise attention", stacklevel=2)
    block = min(128, q.shape[2])
    if k.shape[2] % block:
        block = 1
        for b in range(1, min(129, k.shape[2] + 1)):
            if k.shape[2] % b == 0:
                block = b
    return blockwise_attention(q, k, v, block_size=block,
                               causal=causal, scale=scale)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        block_size: int, causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Streaming attention over KV blocks; O(S·block) memory instead of O(S²)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, h, s, d = q.shape
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if k.shape[2] % block_size:
        raise ValueError(f"key length {k.shape[2]} not divisible by "
                         f"block_size {block_size}")
    n_blocks = k.shape[2] // block_size
    kb = k.reshape(b, h, n_blocks, block_size, d)
    vb = v.reshape(b, h, n_blocks, block_size, d)

    o = jnp.zeros_like(q)
    m = jnp.full((b, h, s), NEG_INF, dtype=q.dtype)
    l = jnp.zeros((b, h, s), dtype=q.dtype)

    qpos = jnp.arange(s)

    # prevent_cse=False: scan's lowering already blocks the CSE hazard,
    # so the default setting would only add unfusable optimization
    # barriers per block (jax.checkpoint docs)
    @functools.partial(jax.checkpoint, prevent_cse=False)
    def body(carry, xs):
        # rematerialized: without checkpoint the backward saves each
        # block's (S x block) score/probability residuals, which across
        # n_blocks totals the O(S^2) dense footprint — recomputing them
        # in the backward is what actually delivers the O(S*block)
        # memory bound (the flash-attention trade, arXiv:2205.14135;
        # measured: un-remat'd S=32k fwd+bwd OOMs this chip's HBM,
        # remat'd runs — BENCH_NOTES.md round-3 long-context table)
        kblk, vblk, blk_idx = xs
        if causal:
            kpos = blk_idx * block_size + jnp.arange(block_size)
            mask = (qpos[:, None] >= kpos[None, :])[None, None]
        else:
            mask = None
        return _block_update(carry, q, kblk, vblk, scale, mask), None

    (o, m, l), _ = jax.lax.scan(
        body, (o, m, l),
        (jnp.moveaxis(kb, 2, 0), jnp.moveaxis(vb, 2, 0),
         jnp.arange(n_blocks)))
    # l == 0 <=> the row never saw a valid key (see _block_update) -> zeros
    return o / jnp.where(l == 0, 1.0, l)[..., None]
