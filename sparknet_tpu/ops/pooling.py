"""Pooling ops with exact reference output-size and divisor semantics
(reference: caffe/src/caffe/layers/pooling_layer.cpp:90-106 ceil-mode shape,
:193-213 AVE divisor counts padding up to H+pad but not window overhang).

Implemented on `lax.reduce_window` so XLA fuses and vectorizes on TPU; the
position-dependent AVE divisor is a host-precomputed static array (shapes are
static under jit, so this costs nothing at runtime).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def pool_out_dim(size: int, kernel: int, pad: int, stride: int) -> int:
    """Ceil-mode output size with boundary trim
    (reference: pooling_layer.cpp:90-105)."""
    out = int(math.ceil((size + 2 * pad - kernel) / float(stride))) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1
    return out


def _window_geometry(size: Tuple[int, int], kernel: Tuple[int, int],
                     pad: Tuple[int, int], stride: Tuple[int, int]):
    h, w = size
    oh = pool_out_dim(h, kernel[0], pad[0], stride[0])
    ow = pool_out_dim(w, kernel[1], pad[1], stride[1])
    # reduce_window needs enough (low, high) padding that every ceil-mode
    # window fits: high pad covers the last window's reach beyond the input.
    hi_h = max((oh - 1) * stride[0] + kernel[0] - h - pad[0], 0)
    hi_w = max((ow - 1) * stride[1] + kernel[1] - w - pad[1], 0)
    return oh, ow, (pad[0], hi_h), (pad[1], hi_w)


def max_pool(x: jax.Array, kernel: Tuple[int, int], *,
             stride: Tuple[int, int] = (1, 1),
             pad: Tuple[int, int] = (0, 0)) -> jax.Array:
    """MAX pooling; padding never wins (reference clips the window to the
    valid region, pooling_layer.cpp:155-169 — identical to -inf padding).

    Gradient: XLA's native SelectAndScatter by default.  An alternative
    custom VJP (kernel-unrolled compare/dilate/add, Caffe-exact first-max
    tie routing) is selectable with SPARKNET_MAXPOOL_BWD=unrolled — it was
    built on the hypothesis that SelectAndScatter dominates the measured
    ~17% max-pool share of the GoogLeNet step, but MEASURED 2.5x SLOWER on
    TPU v5e (9x full-map HBM traffic; GOOGLENET_PROFILE.md round-2 note),
    so the native path stays the default."""
    import os

    if os.environ.get("SPARKNET_MAXPOOL_BWD") == "unrolled":
        return _max_pool(x, tuple(kernel), tuple(stride), tuple(pad))
    return _max_pool_raw(x, tuple(kernel), tuple(stride), tuple(pad))


def _max_pool_raw(x, kernel, stride, pad):
    oh, ow, pad_h, pad_w = _window_geometry(
        (x.shape[2], x.shape[3]), kernel, pad, stride)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, kernel[0], kernel[1]),
        window_strides=(1, 1, stride[0], stride[1]),
        padding=((0, 0), (0, 0), pad_h, pad_w))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool(x, kernel, stride, pad):
    return _max_pool_raw(x, kernel, stride, pad)


def _max_pool_fwd(x, kernel, stride, pad):
    y = _max_pool_raw(x, kernel, stride, pad)
    return y, (x, y)


def _max_pool_bwd(kernel, stride, pad, res, g):
    x, y = res
    n, c, h, w = x.shape
    oh, ow, pad_h, pad_w = _window_geometry((h, w), kernel, pad, stride)
    hp, wp = h + pad_h[0] + pad_h[1], w + pad_w[0] + pad_w[1]
    xp = jnp.pad(x, ((0, 0), (0, 0), pad_h, pad_w),
                 constant_values=-jnp.inf)
    taken = jnp.zeros((n, c, oh, ow), dtype=bool)
    gx = jnp.zeros((n, c, hp, wp), dtype=g.dtype)
    # window positions in the reference's scan order (row-major within the
    # window) so first-wins tie routing matches pooling_layer.cpp exactly
    for i in range(kernel[0]):
        for j in range(kernel[1]):
            patch = lax.slice(
                xp, (0, 0, i, j),
                (n, c, i + (oh - 1) * stride[0] + 1,
                 j + (ow - 1) * stride[1] + 1),
                (1, 1, stride[0], stride[1]))
            win = (patch == y) & ~taken
            taken = taken | win
            contrib = jnp.where(win, g, jnp.zeros((), g.dtype))
            # place contributions back on the strided input grid:
            # interior padding dilates by the stride, low/high shift to
            # window offset (i, j) — pure pad+add, no scatter
            gx = gx + lax.pad(
                contrib, jnp.zeros((), g.dtype),
                ((0, 0, 0), (0, 0, 0),
                 (i, hp - (i + (oh - 1) * stride[0] + 1), stride[0] - 1),
                 (j, wp - (j + (ow - 1) * stride[1] + 1), stride[1] - 1)))
    return (gx[:, :, pad_h[0]:pad_h[0] + h, pad_w[0]:pad_w[0] + w],)


_max_pool.defvjp(_max_pool_fwd, _max_pool_bwd)


def _ave_divisor(size: Tuple[int, int], kernel: Tuple[int, int],
                 pad: Tuple[int, int], stride: Tuple[int, int]) -> np.ndarray:
    """Static (oh, ow) divisor: window extent clipped to [0-pad, size+pad)
    (reference: pooling_layer.cpp:195-201)."""
    h, w = size
    oh = pool_out_dim(h, kernel[0], pad[0], stride[0])
    ow = pool_out_dim(w, kernel[1], pad[1], stride[1])
    div = np.zeros((oh, ow), dtype=np.float32)
    for i in range(oh):
        hstart = i * stride[0] - pad[0]
        hend = min(hstart + kernel[0], h + pad[0])
        for j in range(ow):
            wstart = j * stride[1] - pad[1]
            wend = min(wstart + kernel[1], w + pad[1])
            div[i, j] = (hend - hstart) * (wend - wstart)
    return div


def avg_pool(x: jax.Array, kernel: Tuple[int, int], *,
             stride: Tuple[int, int] = (1, 1),
             pad: Tuple[int, int] = (0, 0)) -> jax.Array:
    """AVE pooling with the reference's padded-divisor semantics."""
    ph, pw = x.shape[2], x.shape[3]
    oh, ow, pad_h, pad_w = _window_geometry((ph, pw), kernel, pad, stride)
    s = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, 1, kernel[0], kernel[1]),
        window_strides=(1, 1, stride[0], stride[1]),
        padding=((0, 0), (0, 0), pad_h, pad_w))
    div = jnp.asarray(_ave_divisor((ph, pw), kernel, pad, stride),
                      dtype=x.dtype)
    return s / div[None, None, :, :]


def stochastic_pool(x: jax.Array, kernel: Tuple[int, int], *,
                    stride: Tuple[int, int] = (1, 1),
                    pad: Tuple[int, int] = (0, 0),
                    rng: Optional[jax.Array] = None,
                    train: bool = True) -> jax.Array:
    """STOCHASTIC pooling (reference: pooling_layer.cu:60-126; train samples a
    window element with probability proportional to its value, test computes
    the activation-weighted average).  Defined for non-negative inputs, as in
    the reference (used after ReLU)."""
    ph, pw = x.shape[2], x.shape[3]
    oh, ow, pad_h, pad_w = _window_geometry((ph, pw), kernel, pad, stride)
    window = dict(window_dimensions=(1, 1, kernel[0], kernel[1]),
                  window_strides=(1, 1, stride[0], stride[1]),
                  padding=((0, 0), (0, 0), pad_h, pad_w))
    s = lax.reduce_window(x, 0.0, lax.add, **window)
    if not train:
        sq = lax.reduce_window(x * x, 0.0, lax.add, **window)
        return jnp.where(s > 0, sq / jnp.where(s > 0, s, 1.0), 0.0)
    if rng is None:
        raise ValueError("stochastic_pool(train=True) needs an rng key")
    # Sample threshold t ~ U(0, sum); pick the first element whose cumulative
    # value crosses t.  Realized as: for threshold t, count elements whose
    # prefix-sum <= t — equivalent to inverse-CDF sampling within the window.
    # We express it with kernel*kernel shifted comparisons (static unroll).
    n, c = x.shape[0], x.shape[1]
    t = jax.random.uniform(rng, (n, c, oh, ow), dtype=x.dtype) * s
    xp = jnp.pad(x, ((0, 0), (0, 0), pad_h, pad_w))
    picked = jnp.zeros((n, c, oh, ow), dtype=x.dtype)
    cum = jnp.zeros((n, c, oh, ow), dtype=x.dtype)
    done = jnp.zeros((n, c, oh, ow), dtype=bool)
    for i in range(kernel[0]):
        for j in range(kernel[1]):
            patch = lax.slice(
                xp, (0, 0, i, j),
                (n, c, i + (oh - 1) * stride[0] + 1,
                 j + (ow - 1) * stride[1] + 1),
                (1, 1, stride[0], stride[1]))
            cum = cum + patch
            hit = (cum >= t) & ~done
            picked = jnp.where(hit, patch, picked)
            done = done | hit
    return picked


def global_pool(x: jax.Array, mode: str = "AVE") -> jax.Array:
    """global_pooling=true: kernel = full spatial extent
    (reference: pooling_layer.cpp:38-42)."""
    if mode == "MAX":
        return jnp.max(x, axis=(2, 3), keepdims=True)
    return jnp.mean(x, axis=(2, 3), keepdims=True)


def spp(x: jax.Array, pyramid_height: int, mode: str = "MAX") -> jax.Array:
    """Spatial pyramid pooling (reference: caffe/src/caffe/layers/spp_layer.cpp):
    for level l, pool into a 2^l × 2^l grid; concat flattened results."""
    outs = []
    h, w = x.shape[2], x.shape[3]
    for l in range(pyramid_height):
        bins = 2 ** l
        kh, kw = int(math.ceil(h / bins)), int(math.ceil(w / bins))
        sh, sw = int(math.floor(h / bins)), int(math.floor(w / bins))
        if bins == 1:
            y = global_pool(x, mode)
        elif mode == "MAX":
            y = max_pool(x, (kh, kw), stride=(sh, sw), pad=(0, 0))
        else:
            y = avg_pool(x, (kh, kw), stride=(sh, sw), pad=(0, 0))
        outs.append(y.reshape(x.shape[0], -1))
    return jnp.concatenate(outs, axis=1)
