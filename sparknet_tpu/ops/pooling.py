"""Pooling ops with exact reference output-size and divisor semantics
(reference: caffe/src/caffe/layers/pooling_layer.cpp:90-106 ceil-mode shape,
:193-213 AVE divisor counts padding up to H+pad but not window overhang).

Implemented on `lax.reduce_window` so XLA fuses and vectorizes on TPU; the
position-dependent AVE divisor is a host-precomputed static array (shapes are
static under jit, so this costs nothing at runtime).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def pool_out_dim(size: int, kernel: int, pad: int, stride: int) -> int:
    """Ceil-mode output size with boundary trim
    (reference: pooling_layer.cpp:90-105)."""
    out = int(math.ceil((size + 2 * pad - kernel) / float(stride))) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1
    return out


def _window_geometry(size: Tuple[int, int], kernel: Tuple[int, int],
                     pad: Tuple[int, int], stride: Tuple[int, int]):
    h, w = size
    oh = pool_out_dim(h, kernel[0], pad[0], stride[0])
    ow = pool_out_dim(w, kernel[1], pad[1], stride[1])
    # reduce_window needs enough (low, high) padding that every ceil-mode
    # window fits: high pad covers the last window's reach beyond the input.
    hi_h = max((oh - 1) * stride[0] + kernel[0] - h - pad[0], 0)
    hi_w = max((ow - 1) * stride[1] + kernel[1] - w - pad[1], 0)
    return oh, ow, (pad[0], hi_h), (pad[1], hi_w)


def max_pool(x: jax.Array, kernel: Tuple[int, int], *,
             stride: Tuple[int, int] = (1, 1),
             pad: Tuple[int, int] = (0, 0)) -> jax.Array:
    """MAX pooling; padding never wins (reference clips the window to the
    valid region, pooling_layer.cpp:155-169 — identical to -inf padding).

    Gradient: XLA's native SelectAndScatter.  It is ~24% of a GoogLeNet
    step (uniform-routing ablation 4,216 -> 5,502 img/s), so five
    alternative formulations were built and measured on TPU v5e; ALL lost
    (unrolled dilate/add 1,654, one-hot grouped conv 1,275, stride-residue
    interleave 2,772 — kept here as "residue" in its faster tree-min tie
    form, 2,635 — and fwd-index 2,650 img/s vs 4,216 native) — the kernel-size many strided passes over the map cost
    more than the select they avoid, and Mosaic rejects strided slices so
    a fused Pallas kernel is blocked (full log: GOOGLENET_PROFILE.md).
    The two instructive variants stay selectable for future hardware:
    SPARKNET_MAXPOOL_BWD=unrolled|residue (both Caffe-exact first-max tie
    routing, gradient-equivalence tested) and =uniform (attribution only,
    wrong gradients)."""
    import os

    impl = os.environ.get("SPARKNET_MAXPOOL_BWD")
    if impl == "unrolled":
        return _max_pool(x, tuple(kernel), tuple(stride), tuple(pad))
    if impl == "uniform":  # ATTRIBUTION ONLY: wrong gradients (AVE-style
        # uniform routing) to isolate SelectAndScatter's cost from the
        # backward's data movement
        return _max_pool_uniform_bwd(x, tuple(kernel), tuple(stride),
                                     tuple(pad))
    if impl == "residue":
        return _max_pool_residue(x, tuple(kernel), tuple(stride),
                                 tuple(pad))
    if impl not in (None, "", "native"):
        raise ValueError(
            f"SPARKNET_MAXPOOL_BWD={impl!r}: expected native, unrolled, "
            f"residue, or uniform (the other formulations from the "
            f"GOOGLENET_PROFILE.md study were removed as strictly worse)")
    return _max_pool_raw(x, tuple(kernel), tuple(stride), tuple(pad))




@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool_residue(x, kernel, stride, pad):
    return _max_pool_raw(x, kernel, stride, pad)


def _max_pool_residue_fwd(x, kernel, stride, pad):
    y = _max_pool_raw(x, kernel, stride, pad)
    return y, (x, y)


def _max_pool_residue_bwd(kernel, stride, pad, res, g):
    """Exact max routing via stride-residue decomposition.

    Input row u receives only from window offsets i with i ≡ u+pad (mod
    stride), so the scatter splits into stride² independent CLASS maps:
    each of the kernel's one-hot masks accumulates (with an integer shift)
    into its class on the SMALL pooled grid, and one interleaving reshape
    assembles gx — one full-map write, no SelectAndScatter, no dilated
    conv.  First-max-wins tie routing as pooling_layer.cpp:163-168."""
    x, y = res
    n, c, h, w = x.shape
    kh, kw = kernel
    sh, sw = stride
    oh, ow, pad_h, pad_w = _window_geometry((h, w), kernel, pad, stride)
    hp, wp = h + pad_h[0] + pad_h[1], w + pad_w[0] + pad_w[1]
    lh, lw = -(-hp // sh), -(-wp // sw)
    xp = jnp.pad(x, ((0, 0), (0, 0), pad_h, pad_w),
                 constant_values=-jnp.inf)
    # first-max-wins via a parallel tree-min over offset indices (no
    # sequential taken-chain): eq masks and the min combine in parallel
    eqs = []
    first = None
    big = jnp.int32(kh * kw)
    for i in range(kh):
        for j in range(kw):
            patch = lax.slice(
                xp, (0, 0, i, j),
                (n, c, i + (oh - 1) * sh + 1, j + (ow - 1) * sw + 1),
                (1, 1, sh, sw))
            eq = patch == y
            eqs.append(eq)
            cand = jnp.where(eq, jnp.int32(i * kw + j), big)
            first = cand if first is None else jnp.minimum(first, cand)
    zero = jnp.zeros((n, c, lh, lw), dtype=g.dtype)
    classes = [[zero] * sw for _ in range(sh)]
    for i in range(kh):
        for j in range(kw):
            win = eqs[i * kw + j] & (first == i * kw + j)
            m = jnp.where(win, g, jnp.zeros((), g.dtype))
            dh, dw = i // sh, j // sw
            shifted = jnp.pad(m, ((0, 0), (0, 0),
                                  (dh, lh - oh - dh),
                                  (dw, lw - ow - dw)))
            classes[i % sh][j % sw] = classes[i % sh][j % sw] + shifted
    # interleave class maps: (n, c, lh, sh, lw, sw) -> (n, c, lh*sh, lw*sw)
    grid = jnp.stack([jnp.stack(row, axis=-1) for row in classes],
                     axis=-3)  # rows: (n,c,lh,lw,sw) -> (n,c,lh,sh,lw,sw)
    gx = grid.reshape(n, c, lh * sh, lw * sw)
    return (lax.slice(gx, (0, 0, pad_h[0], pad_w[0]),
                      (n, c, pad_h[0] + h, pad_w[0] + w)),)


_max_pool_residue.defvjp(_max_pool_residue_fwd, _max_pool_residue_bwd)






@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool_uniform_bwd(x, kernel, stride, pad):
    return _max_pool_raw(x, kernel, stride, pad)


def _max_pool_uniform_fwd_rule(x, kernel, stride, pad):
    return _max_pool_raw(x, kernel, stride, pad), x.shape


def _max_pool_uniform_bwd_rule(kernel, stride, pad, x_shape, g):
    # route g/|window| uniformly — the transpose of AVE pooling's sum,
    # which XLA lowers to a dilated reduce_window (no select)
    n, c, h, w = x_shape
    oh, ow, pad_h, pad_w = _window_geometry((h, w), kernel, pad, stride)
    gd = lax.pad(g / (kernel[0] * kernel[1]), jnp.zeros((), g.dtype),
                 ((0, 0, 0), (0, 0, 0),
                  (kernel[0] - 1 - pad_h[0], kernel[0] - 1 - pad_h[1],
                   stride[0] - 1),
                  (kernel[1] - 1 - pad_w[0], kernel[1] - 1 - pad_w[1],
                   stride[1] - 1)))
    gx = lax.reduce_window(
        gd, 0.0, lax.add, window_dimensions=(1, 1, kernel[0], kernel[1]),
        window_strides=(1, 1, 1, 1), padding="VALID")
    return (gx[:, :, :h, :w],)


_max_pool_uniform_bwd.defvjp(_max_pool_uniform_fwd_rule,
                             _max_pool_uniform_bwd_rule)


def _max_pool_raw(x, kernel, stride, pad):
    oh, ow, pad_h, pad_w = _window_geometry(
        (x.shape[2], x.shape[3]), kernel, pad, stride)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, kernel[0], kernel[1]),
        window_strides=(1, 1, stride[0], stride[1]),
        padding=((0, 0), (0, 0), pad_h, pad_w))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool(x, kernel, stride, pad):
    return _max_pool_raw(x, kernel, stride, pad)


def _max_pool_fwd(x, kernel, stride, pad):
    y = _max_pool_raw(x, kernel, stride, pad)
    return y, (x, y)


def _max_pool_bwd(kernel, stride, pad, res, g):
    x, y = res
    n, c, h, w = x.shape
    oh, ow, pad_h, pad_w = _window_geometry((h, w), kernel, pad, stride)
    hp, wp = h + pad_h[0] + pad_h[1], w + pad_w[0] + pad_w[1]
    xp = jnp.pad(x, ((0, 0), (0, 0), pad_h, pad_w),
                 constant_values=-jnp.inf)
    taken = jnp.zeros((n, c, oh, ow), dtype=bool)
    gx = jnp.zeros((n, c, hp, wp), dtype=g.dtype)
    # window positions in the reference's scan order (row-major within the
    # window) so first-wins tie routing matches pooling_layer.cpp exactly
    for i in range(kernel[0]):
        for j in range(kernel[1]):
            patch = lax.slice(
                xp, (0, 0, i, j),
                (n, c, i + (oh - 1) * stride[0] + 1,
                 j + (ow - 1) * stride[1] + 1),
                (1, 1, stride[0], stride[1]))
            win = (patch == y) & ~taken
            taken = taken | win
            contrib = jnp.where(win, g, jnp.zeros((), g.dtype))
            # place contributions back on the strided input grid:
            # interior padding dilates by the stride, low/high shift to
            # window offset (i, j) — pure pad+add, no scatter
            gx = gx + lax.pad(
                contrib, jnp.zeros((), g.dtype),
                ((0, 0, 0), (0, 0, 0),
                 (i, hp - (i + (oh - 1) * stride[0] + 1), stride[0] - 1),
                 (j, wp - (j + (ow - 1) * stride[1] + 1), stride[1] - 1)))
    return (gx[:, :, pad_h[0]:pad_h[0] + h, pad_w[0]:pad_w[0] + w],)


_max_pool.defvjp(_max_pool_fwd, _max_pool_bwd)


def _ave_divisor(size: Tuple[int, int], kernel: Tuple[int, int],
                 pad: Tuple[int, int], stride: Tuple[int, int]) -> np.ndarray:
    """Static (oh, ow) divisor: window extent clipped to [0-pad, size+pad)
    (reference: pooling_layer.cpp:195-201)."""
    h, w = size
    oh = pool_out_dim(h, kernel[0], pad[0], stride[0])
    ow = pool_out_dim(w, kernel[1], pad[1], stride[1])
    div = np.zeros((oh, ow), dtype=np.float32)
    for i in range(oh):
        hstart = i * stride[0] - pad[0]
        hend = min(hstart + kernel[0], h + pad[0])
        for j in range(ow):
            wstart = j * stride[1] - pad[1]
            wend = min(wstart + kernel[1], w + pad[1])
            div[i, j] = (hend - hstart) * (wend - wstart)
    return div


def avg_pool(x: jax.Array, kernel: Tuple[int, int], *,
             stride: Tuple[int, int] = (1, 1),
             pad: Tuple[int, int] = (0, 0)) -> jax.Array:
    """AVE pooling with the reference's padded-divisor semantics."""
    ph, pw = x.shape[2], x.shape[3]
    oh, ow, pad_h, pad_w = _window_geometry((ph, pw), kernel, pad, stride)
    s = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, 1, kernel[0], kernel[1]),
        window_strides=(1, 1, stride[0], stride[1]),
        padding=((0, 0), (0, 0), pad_h, pad_w))
    div = jnp.asarray(_ave_divisor((ph, pw), kernel, pad, stride),
                      dtype=x.dtype)
    return s / div[None, None, :, :]


def stochastic_pool(x: jax.Array, kernel: Tuple[int, int], *,
                    stride: Tuple[int, int] = (1, 1),
                    pad: Tuple[int, int] = (0, 0),
                    rng: Optional[jax.Array] = None,
                    train: bool = True) -> jax.Array:
    """STOCHASTIC pooling (reference: pooling_layer.cu:60-126; train samples a
    window element with probability proportional to its value, test computes
    the activation-weighted average).  Defined for non-negative inputs, as in
    the reference (used after ReLU)."""
    ph, pw = x.shape[2], x.shape[3]
    oh, ow, pad_h, pad_w = _window_geometry((ph, pw), kernel, pad, stride)
    window = dict(window_dimensions=(1, 1, kernel[0], kernel[1]),
                  window_strides=(1, 1, stride[0], stride[1]),
                  padding=((0, 0), (0, 0), pad_h, pad_w))
    s = lax.reduce_window(x, 0.0, lax.add, **window)
    if not train:
        sq = lax.reduce_window(x * x, 0.0, lax.add, **window)
        return jnp.where(s > 0, sq / jnp.where(s > 0, s, 1.0), 0.0)
    if rng is None:
        raise ValueError("stochastic_pool(train=True) needs an rng key")
    # Sample threshold t ~ U(0, sum); pick the first element whose cumulative
    # value crosses t.  Realized as: for threshold t, count elements whose
    # prefix-sum <= t — equivalent to inverse-CDF sampling within the window.
    # We express it with kernel*kernel shifted comparisons (static unroll).
    n, c = x.shape[0], x.shape[1]
    t = jax.random.uniform(rng, (n, c, oh, ow), dtype=x.dtype) * s
    xp = jnp.pad(x, ((0, 0), (0, 0), pad_h, pad_w))
    picked = jnp.zeros((n, c, oh, ow), dtype=x.dtype)
    cum = jnp.zeros((n, c, oh, ow), dtype=x.dtype)
    done = jnp.zeros((n, c, oh, ow), dtype=bool)
    for i in range(kernel[0]):
        for j in range(kernel[1]):
            patch = lax.slice(
                xp, (0, 0, i, j),
                (n, c, i + (oh - 1) * stride[0] + 1,
                 j + (ow - 1) * stride[1] + 1),
                (1, 1, stride[0], stride[1]))
            cum = cum + patch
            hit = (cum >= t) & ~done
            picked = jnp.where(hit, patch, picked)
            done = done | hit
    return picked


def global_pool(x: jax.Array, mode: str = "AVE") -> jax.Array:
    """global_pooling=true: kernel = full spatial extent
    (reference: pooling_layer.cpp:38-42)."""
    if mode == "MAX":
        return jnp.max(x, axis=(2, 3), keepdims=True)
    return jnp.mean(x, axis=(2, 3), keepdims=True)


def spp(x: jax.Array, pyramid_height: int, mode: str = "MAX") -> jax.Array:
    """Spatial pyramid pooling (reference: caffe/src/caffe/layers/spp_layer.cpp):
    for level l, pool into a 2^l × 2^l grid; concat flattened results."""
    outs = []
    h, w = x.shape[2], x.shape[3]
    for l in range(pyramid_height):
        bins = 2 ** l
        kh, kw = int(math.ceil(h / bins)), int(math.ceil(w / bins))
        sh, sw = int(math.floor(h / bins)), int(math.floor(w / bins))
        if bins == 1:
            y = global_pool(x, mode)
        elif mode == "MAX":
            y = max_pool(x, (kh, kw), stride=(sh, sw), pad=(0, 0))
        else:
            y = avg_pool(x, (kh, kw), stride=(sh, sw), pad=(0, 0))
        outs.append(y.reshape(x.shape[0], -1))
    return jnp.concatenate(outs, axis=1)
