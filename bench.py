"""Benchmark: AlexNet training throughput (img/s) on one chip.

Baseline (BASELINE.md): the reference's headline number is CaffeNet/AlexNet
training at ~267 img/s on a K40 with cuDNN (caffe/docs/performance_hardware.md:
19-24, 26.5s / 20 iters x 256 imgs without cuDNN, 19.2s with).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_IMGS_PER_SEC = 267.0  # K40 + cuDNN
BATCH = 256
WARMUP_STEPS = 3
MEASURE_STEPS = 20  # the reference's own protocol: 20 iters of 256 imgs


def main() -> None:
    import jax
    import jax.numpy as jnp

    from sparknet_tpu.utils.compile_cache import maybe_enable_compile_cache

    maybe_enable_compile_cache()

    from sparknet_tpu.core.net import Net
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver.solver import make_single_step
    from sparknet_tpu.solver import updates

    net_param = caffe_pb.load_net_prototxt(
        "/root/reference/caffe/models/bvlc_alexnet/train_val.prototxt")
    net = Net(net_param, "TRAIN", batch_override=BATCH)
    sp = caffe_pb.load_solver_prototxt(
        "/root/reference/caffe/models/bvlc_alexnet/solver.prototxt")

    params = net.init_params(seed=0)
    state = updates.init_state(params, sp.resolved_type())
    # bf16 mixed precision (fp32 masters) — the TPU-native training config;
    # ~15% over fp32 on this net, identical loss trajectory within bf16
    # resolution (tests/test_precision.py)
    step = jax.jit(make_single_step(net, sp, precision="bfloat16"),
                   donate_argnums=(0, 1))

    rng = np.random.RandomState(0)
    data = jnp.asarray(rng.rand(BATCH, 3, 227, 227).astype(np.float32))
    label = jnp.asarray(rng.randint(0, 1000, size=(BATCH,)).astype(np.int32))
    key = jax.random.PRNGKey(0)

    it = [0]

    def run_chain(n: int) -> float:
        """Run n dependent steps and force materialization by fetching the
        loss scalar.  Returns wall time including one fixed host<->device
        fetch; the caller differences two chain lengths to cancel it
        (block_until_ready alone is unreliable on tunneled platforms)."""
        nonlocal params, state
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            params, state, loss = step(params, state, jnp.int32(it[0]),
                                       {"data": data, "label": label},
                                       jax.random.fold_in(key, it[0]))
            it[0] += 1
        float(loss)
        return time.perf_counter() - t0

    run_chain(WARMUP_STEPS)  # compile + warm caches
    # the shared chip's throughput drifts run to run; take the median of
    # three differenced windows so one slow window doesn't define the number
    rates = []
    for _ in range(3):
        short = run_chain(2)
        long = run_chain(2 + MEASURE_STEPS)
        rates.append(MEASURE_STEPS * BATCH / (long - short))
    imgs_per_sec = float(np.median(rates))
    print(json.dumps({
        "metric": "alexnet_train_imgs_per_sec",
        "value": round(imgs_per_sec, 1),
        "unit": "img/s",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
