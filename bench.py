"""Benchmark: training throughput + MFU on one chip, device-resident AND
host-fed.

Baseline (BASELINE.md): the reference's headline number is CaffeNet/AlexNet
training at ~267 img/s on a K40 with cuDNN (caffe/docs/performance_hardware.md:
19-24, 26.5s / 20 iters x 256 imgs without cuDNN, 19.2s with) — a number that
includes Caffe's real prefetching data layer, so the honest comparison here is
the HOST-FED figure: fresh uint8 batches pulled through DataTransformer
(random crop 227 from 256 + mean subtract + mirror) and device_put each step,
overlapped with compute the way the integrated hot path works
(DistributedSolver.set_prefetch / native prefetcher).

Emits per-model lines on stderr and ONE JSON line on stdout (the driver
contract).  The headline metric stays `alexnet_train_imgs_per_sec` =
device-resident AlexNet; `host_fed_imgs_per_sec`, `mfu`, and the `googlenet_*`
fields ride along in the same object.
"""

import json
import os
import queue
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_IMGS_PER_SEC = 267.0  # K40 + cuDNN
WARMUP_STEPS = 3
MEASURE_STEPS = 20  # the reference's own protocol: 20 iters


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def build(model_dir, batch, precision="bfloat16", transform=None):
    """Returns (net, jitted_step, params, state).  `transform` fuses a
    device-side data transform in front of the step under the same jit."""
    import jax

    from sparknet_tpu.core.net import Net
    from sparknet_tpu.ops.device_transform import fuse_transform_into_step
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.solver import updates
    from sparknet_tpu.solver.solver import make_single_step

    net_param = caffe_pb.load_net_prototxt(
        os.path.join(model_dir, "train_val.prototxt"))
    net = Net(net_param, "TRAIN", batch_override=batch)
    sp = caffe_pb.load_solver_prototxt(
        os.path.join(model_dir, "solver.prototxt"))
    params = net.init_params(seed=0)
    state = updates.init_state(params, sp.resolved_type())
    step = make_single_step(net, sp, precision=precision)
    if transform is not None:
        step = fuse_transform_into_step(transform, step)
    return net, jax.jit(step, donate_argnums=(0, 1)), params, state


def measure_chain(step, params, state, batch_fn, batch):
    """Median img/s over three differenced windows (chain of dependent
    steps; differencing two chain lengths cancels the fixed host<->device
    fetch, which block_until_ready alone does not on tunneled platforms)."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    it = [0]
    ps = [params, state]

    def run_chain(n):
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            ps[0], ps[1], loss = step(ps[0], ps[1], jnp.int32(it[0]),
                                      batch_fn(), jax.random.fold_in(
                                          key, it[0]))
            it[0] += 1
        float(loss)
        return time.perf_counter() - t0

    from sparknet_tpu.utils.timers import differenced_chain_s

    return batch / differenced_chain_s(run_chain, MEASURE_STEPS,
                                       warmup=WARMUP_STEPS)


def bench_model(name, model_dir, batch, crop, n_classes=1000):
    """Device-resident and host-fed throughput + MFU for one model."""
    import jax
    import jax.numpy as jnp

    from sparknet_tpu.utils.flops import peak_flops, training_flops_per_iter

    net, step, params, state = build(model_dir, batch)
    flops_iter = training_flops_per_iter(net)
    peak = peak_flops(jax.devices()[0])

    rng = np.random.RandomState(0)
    data = jnp.asarray(rng.rand(batch, 3, crop, crop).astype(np.float32))
    label = jnp.asarray(rng.randint(0, n_classes, size=(batch,))
                        .astype(np.int32))
    resident = measure_chain(step, params, state,
                             lambda: {"data": data, "label": label}, batch)
    res_mfu = flops_iter * resident / batch / peak

    # ---- fused transform, device-resident uint8: the full data-path
    # arithmetic (random crop 227/224 from 256 + mirror + mean subtract,
    # ops/device_transform.py) fused into the compiled step — isolates the
    # augmentation cost from wire bandwidth
    from sparknet_tpu.ops.device_transform import make_device_transformer

    full = 256  # canonical source size (ImageNetApp.scala:20-26)
    pool_dev_np = rng.randint(0, 256, size=(batch, 3, full, full)
                              ).astype(np.uint8)
    tf = make_device_transformer(
        crop_size=crop, mirror=True,
        mean_image=pool_dev_np.mean(axis=0, dtype=np.float32),
        phase="TRAIN")
    _nf, fused_step, params_f, state_f = build(model_dir, batch,
                                               transform=tf)
    pool_dev = {"data": jax.device_put(pool_dev_np),
                "label": jax.device_put(rng.randint(
                    0, n_classes, size=(batch,)).astype(np.int32))}
    fused = measure_chain(fused_step, params_f, state_f,
                          lambda: pool_dev, batch)

    # ---- host-fed: fresh uint8 256x256 batches each step, RAW bytes over
    # the wire, with the crop/mirror/mean transform fused INTO the compiled
    # step (ops/device_transform.py) — the TPU-native split of the
    # reference's host-side data layer: the host only assembles bytes; the
    # augmentation arithmetic rides the MXU program.  A producer thread
    # stages batch N+1's device_put while step N computes (the
    # set_prefetch / native-feed pattern).
    pool = rng.randint(0, 256, size=(4 * batch, 3, full, full)
                       ).astype(np.uint8)
    labels_pool = rng.randint(0, n_classes, size=(4 * batch,)
                              ).astype(np.int32)
    # fresh params/state: the fused run above donated its buffers
    _n3, step2, params2, state2 = build(model_dir, batch, transform=tf)

    q: "queue.Queue" = queue.Queue(maxsize=3)
    stop = threading.Event()

    producer_err = []

    def producer():
        try:
            i = 0
            while not stop.is_set():
                sel = (np.arange(batch) + i * batch) % len(pool)
                batch_dev = {"data": jax.device_put(pool[sel]),
                             "label": jax.device_put(labels_pool[sel])}
                i += 1
                while not stop.is_set():
                    try:
                        q.put(batch_dev, timeout=0.2)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:
            producer_err.append(e)

    def take():
        # bounded wait so a dead producer fails the bench loudly instead
        # of hanging the driver
        while True:
            if producer_err:
                raise RuntimeError("bench producer died") from \
                    producer_err[0]
            try:
                return q.get(timeout=60)
            except queue.Empty:
                raise RuntimeError("bench producer stalled >60s")

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    try:
        hosted = measure_chain(step2, params2, state2, take, batch)
    finally:
        stop.set()
        while not q.empty():
            q.get_nowait()
        th.join(timeout=5)
    hosted_mfu = flops_iter * hosted / batch / peak

    # measured wire speed for one uint8 batch, post-program-execution (on
    # tunneled dev platforms this degrades ~50x from the fresh-process
    # rate; on a real TPU-VM the PCIe path does not — see BENCH_NOTES.md)
    t0 = time.perf_counter()
    jax.device_put(pool[:batch]).block_until_ready()
    wire_mbps = pool[:batch].nbytes / (time.perf_counter() - t0) / 1e6

    out = {"model": name, "batch": batch,
           "device_resident_imgs_per_sec": round(resident, 1),
           "fused_transform_imgs_per_sec": round(fused, 1),
           "host_fed_imgs_per_sec": round(hosted, 1),
           "mfu": round(res_mfu, 4),
           "host_fed_mfu": round(hosted_mfu, 4),
           "train_gflops_per_img": round(flops_iter / batch / 1e9, 2),
           "wire_mbps_post_exec": round(wire_mbps, 1)}
    log(json.dumps(out))
    return out


def bench_inference(name, model_dir, batch, fuse_1x1=False):
    """Deploy-form forward throughput — the serving / `caffe test` path.

    Reference baseline: CaffeNet tests 50k val images in 60.7 s with cuDNN
    on a K40 (caffe/docs/performance_hardware.md:19-24) = ~823 img/s.
    bf16 params/activations (TPU serving practice; no optimizer state, no
    label input).  Deploy nets carry no aux heads, so this leg is also
    where the inception 1x1 fusion pass (core/fuse.py) gets its honest
    shot per the GOOGLENET_PROFILE.md anomaly."""
    import jax
    import jax.numpy as jnp

    from sparknet_tpu.core.net import Net
    from sparknet_tpu.proto import caffe_pb
    from sparknet_tpu.utils.flops import forward_macs, peak_flops

    path = (model_dir if model_dir.endswith(".prototxt")
            else os.path.join(model_dir, "deploy.prototxt"))
    net_param = caffe_pb.load_net_prototxt(path)
    # deploy prototxts declare a placeholder batch (10); serve at ours
    for s in net_param.msg.getlist("input_shape"):
        dims = [int(d) for d in s.getlist("dim")]
        s.set_list("dim", [batch] + dims[1:])
    if net_param.msg.has("input_dim"):
        # legacy form: a flat list, 4 dims per declared input
        dims = [int(d) for d in net_param.msg.getlist("input_dim")]
        for i in range(0, len(dims), 4):
            dims[i] = batch
        net_param.msg.set_list("input_dim", dims)
    if fuse_1x1:
        from sparknet_tpu.core.fuse import fuse_sibling_1x1_convs

        net_param, _map, groups = fuse_sibling_1x1_convs(net_param)
        if not groups:
            raise RuntimeError("fusion pass changed nothing")
    net = Net(net_param, "TEST")
    params = net.init_params(seed=0)
    in_blob = net.input_blobs[0]
    out_blob = net.output_blobs[-1]
    fwd_flops = 2.0 * sum(forward_macs(net).values())
    peak = peak_flops(jax.devices()[0])

    # one-time load-time cast, OUTSIDE the timed step — a real bf16
    # serving deployment converts weights once, so the per-step program
    # must not re-cast ~100s of MB each call (stat blobs stay fp32, as
    # in make_loss_fn)
    stat_keys = set(net.stat_keys())
    params = {k: (v.astype(jnp.bfloat16)
                  if (k not in stat_keys
                      and jnp.issubdtype(v.dtype, jnp.floating)) else v)
              for k, v in params.items()}

    def forward(p, data, salt):
        blobs = net.forward(p, {in_blob: (data + salt)
                                .astype(jnp.bfloat16)})
        out = blobs[out_blob]
        # successive calls must form a TRUE dependency chain with
        # genuinely different arguments: salt_{n+1} is a function of
        # out_n, and data+salt differs bitwise every call.  Without this
        # the steps are identical independent programs and what gets
        # measured is dispatch (or a cached replay), not execution —
        # same role as the params/state threading in measure_chain.
        return out, salt + out.reshape(-1)[0].astype(salt.dtype) + 1e-3

    jfwd = jax.jit(forward)
    rng = np.random.RandomState(0)
    # input geometry comes from the (batch-rewritten) deploy declaration
    data = jnp.asarray(rng.rand(*net.blob_shapes[in_blob])
                       .astype(np.float32))
    salt = jnp.float32(0.0)

    def run_chain(n):
        nonlocal salt
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out, salt = jfwd(params, data, salt)
        # fetch a VALUE, not block_until_ready: on the tunneled platform
        # block returns before deferred execution completes, and only a
        # real transfer forces the chain (measure_chain's float(loss)
        # plays the same role; differencing cancels the fetch latency)
        float(out.reshape(-1)[0])
        return time.perf_counter() - t0

    from sparknet_tpu.utils.timers import differenced_chain_s

    infer = batch / differenced_chain_s(run_chain, MEASURE_STEPS,
                                        warmup=WARMUP_STEPS)
    out = {"model": name, "batch": batch, "fused_1x1": bool(fuse_1x1),
           "infer_imgs_per_sec": round(infer, 1),
           "infer_mfu": round(fwd_flops * infer / batch / peak, 4)}
    log(json.dumps(out))
    return out


def bench_serving(model: str = "lenet", offered_qps: float = 200.0,
                  n_requests: int = 400, max_batch: int = 8,
                  max_wait_ms: float = 4.0, seed: int = 0,
                  quant: str = None, min_fill: int = None,
                  replicas: int = None) -> dict:
    """Online-serving latency + throughput at a fixed offered load: the
    serving engine (sparknet_tpu/serving/) fronting LeNet on the CPU
    backend, driven open-loop with Poisson arrivals — p50/p99 response
    latency and achieved QPS under micro-batching.

    CPU on purpose: the serving numbers must stay comparable across
    driver runs whether or not the axon tunnel has a window open, and
    the tunnel's 65-100 ms fetch RTT would swamp millisecond-scale
    online latencies anyway (BENCH_NOTES.md) — model-level TPU serving
    throughput is already covered by the bench_inference legs.

    `quant` (serving/quant.py: "bf16"/"int8") reruns the same protocol
    through the quantized forward; its fields land under a
    serving_<quant>_ prefix plus the calibration top-1 agreement and the
    packed param bytes, so the driver record shows the quantized path's
    latency AND its fidelity side by side with fp32."""
    import jax

    from sparknet_tpu.serving import (InferenceServer, ServerConfig,
                                      ServerOverloaded)

    try:
        cpus = jax.devices("cpu")
    except RuntimeError:
        cpus = None  # CPU backend unavailable: serve on the default device
    cfg = ServerConfig(max_batch=max_batch, max_wait_ms=max_wait_ms,
                       queue_depth=16 * max_batch)
    if min_fill is not None:
        cfg.min_fill = min_fill
    server = InferenceServer(cfg, devices=cpus)
    try:
        if replicas is not None and replicas != 1:
            lm = server.load(model, quant=quant, replicas=replicas)
        else:
            lm = server.load(model, device=cpus[0] if cpus else None,
                             quant=quant)
        shape = lm.runner.sample_shape
        rng = np.random.RandomState(seed)
        pool = rng.rand(32, *shape).astype(np.float32)
        gaps = rng.exponential(1.0 / offered_qps, size=n_requests)
        futs = []
        rejected = 0
        t0 = time.perf_counter()
        next_t = t0
        for i in range(n_requests):
            next_t += gaps[i]
            now = time.perf_counter()
            if next_t > now:
                time.sleep(next_t - now)
            try:
                futs.append(server.submit(model, pool[i % len(pool)]))
            except ServerOverloaded:
                rejected += 1
        for f in futs:
            f.result(timeout=120)
        elapsed = time.perf_counter() - t0
        st = server.stats()["models"][model]
    finally:
        server.close(drain=True)
    pfx = "serving" if quant in (None, "fp32") else f"serving_{quant}"
    out = {f"{pfx}_model": model,
           f"{pfx}_offered_qps": round(offered_qps, 1),
           f"{pfx}_qps": round(st["completed"] / elapsed, 1),
           f"{pfx}_p50_ms": st["total_ms"]["p50_ms"],
           f"{pfx}_p99_ms": st["total_ms"]["p99_ms"],
           f"{pfx}_batch_occupancy": st["batch_occupancy_mean"],
           f"{pfx}_rejected": rejected,
           f"{pfx}_compiles": st["engine_compiles"],
           f"{pfx}_replicas": lm.n_replicas,
           f"{pfx}_topology": _serving_topology(cpus)}
    if pfx != "serving":
        out[f"{pfx}_agreement"] = lm.runner.quant_agreement
        out[f"{pfx}_param_bytes"] = lm.runner.param_bytes
    log(json.dumps(out))
    return out


def _serving_topology(devices) -> str:
    """'8xcpu'-style mesh stamp for serving records: device count x
    platform of the pool serving replicas place on."""
    if not devices:
        return "0xnone"
    return f"{len(devices)}x{getattr(devices[0], 'platform', 'unknown')}"


def bench_serving_mesh(model: str = "lenet", n_requests: int = 192,
                       max_batch: int = 8, seed: int = 0,
                       replicas: int = 0, rounds: int = 3) -> dict:
    """Mesh-replicated vs single-replica serving, interleaved A/B: the
    SAME closed-loop burst (n_requests admitted with backpressure, wait
    for every response) alternates between a one-replica server and a
    server whose model is placed across every CPU device (replicas=0 =
    one per device), `rounds` times A/B/A/B so tunnel-noise-style drift
    hits both arms equally (CLAUDE.md measurement discipline; this leg
    is CPU-only so the main noise source is host contention itself).

    QPS is the median over rounds; latency percentiles pool all rounds.
    `serving_mesh_speedup` is the honest ratio — on a single-core host
    the N virtual devices share one core, so the mesh arm mostly
    measures scheduler overhead there (the ≥4x ROADMAP target needs N
    real cores/chips; BENCH_NOTES.md records what this box can show)."""
    import jax

    from sparknet_tpu.serving import InferenceServer, ServerConfig

    try:
        devs = jax.devices("cpu")
    except RuntimeError:
        devs = jax.devices()
    n_rep = len(devs) if replicas == 0 else int(replicas)

    def make(n):
        srv = InferenceServer(
            ServerConfig(max_batch=max_batch,
                         queue_depth=max(2 * n_requests, 64)),
            devices=devs)
        if n == 1:
            lm = srv.load(model, device=devs[0])
        else:
            lm = srv.load(model, replicas=n)
        return srv, lm

    single, lm1 = make(1)
    mesh, lmN = make(n_rep)
    shape = lm1.runner.sample_shape
    pool = np.random.RandomState(seed).rand(
        64, *shape).astype(np.float32)
    reqs = [pool[i % len(pool)] for i in range(n_requests)]

    def measure(srv):
        t0 = time.perf_counter()
        futs = srv.submit_many(model, reqs, wait=True)
        lat = [f.result(timeout=600).total_ms for f in futs]
        return n_requests / (time.perf_counter() - t0), lat

    qps1, qpsN, lat1, latN = [], [], [], []
    try:
        for _ in range(max(1, int(rounds))):
            q, l = measure(single)
            qps1.append(q)
            lat1 += l
            q, l = measure(mesh)
            qpsN.append(q)
            latN += l
        compiles = max(r.compile_count() for r in lmN.replicas)
    finally:
        single.close(drain=True)
        mesh.close(drain=True)
    q1 = float(np.median(qps1))
    qN = float(np.median(qpsN))
    out = {"serving_mesh_model": model,
           "serving_mesh_replicas": lmN.n_replicas,
           "serving_mesh_topology": _serving_topology(devs),
           "serving_mesh_rounds": int(rounds),
           "serving_mesh_n_requests": int(n_requests),
           "serving_mesh_qps": round(qN, 1),
           "serving_mesh_p50_ms": round(float(np.percentile(latN, 50)), 3),
           "serving_mesh_p99_ms": round(float(np.percentile(latN, 99)), 3),
           "serving_single_qps": round(q1, 1),
           "serving_single_p50_ms": round(float(np.percentile(lat1, 50)),
                                          3),
           "serving_single_p99_ms": round(float(np.percentile(lat1, 99)),
                                          3),
           "serving_mesh_speedup": round(qN / q1, 3) if q1 else None,
           "serving_mesh_compiles": compiles}
    log(json.dumps(out))
    return out


def bench_serving_sharded(model: str = "lenet", n_requests: int = 192,
                          max_batch: int = 8, seed: int = 0,
                          shards: int = 4, rounds: int = 3) -> dict:
    """Sharded vs unsharded serving, interleaved A/B: one replica whose
    params live gspmd-sharded over a `shards`-device mesh slice
    (all-gathered at use inside the jitted forward — README "Sharded
    serving") against one single-device unsharded replica, the SAME
    closed-loop burst alternating A/B/A/B `rounds` times so host-noise
    drift hits both arms equally (CLAUDE.md measurement discipline;
    CPU-only leg).

    Besides QPS/latency the leg lands the two claims the sharded path
    makes: `serving_sharded_bitwise` (an idle-server bucket-1 probe —
    same sample through both arms must agree to the BIT, the
    gather-at-use design guarantee) and
    `serving_sharded_post_warmup_compiles` (0 = the burst never
    recompiled; gspmd shardings are part of the warmed cache key).  On
    one physical core the slice shares a core with itself, so the ratio
    mostly prices the gather + partitioner overhead — the honest stamp,
    as with serving_mesh."""
    import jax

    from sparknet_tpu.serving import InferenceServer, ServerConfig

    try:
        devs = jax.devices("cpu")
    except RuntimeError:
        devs = jax.devices()
    shards = int(shards)
    if len(devs) < shards:
        raise RuntimeError(
            f"serving_sharded needs {shards} devices, have {len(devs)} "
            f"(XLA_FLAGS=--xla_force_host_platform_device_count=8)")

    def make(n_shards):
        srv = InferenceServer(
            ServerConfig(max_batch=max_batch,
                         queue_depth=max(2 * n_requests, 64)),
            devices=devs)
        if n_shards == 1:
            lm = srv.load(model, device=devs[0])
        else:
            lm = srv.load(model, replicas=1, shards=n_shards)
        return srv, lm

    single, lm1 = make(1)
    sharded, lmS = make(shards)
    warm_compiles = lmS.replicas[0].compile_count()
    shape = lm1.runner.sample_shape
    pool = np.random.RandomState(seed).rand(
        64, *shape).astype(np.float32)
    reqs = [pool[i % len(pool)] for i in range(n_requests)]

    # bitwise probe while both servers are idle: the same sample rides
    # a bucket-1 batch through each arm
    p1 = single.submit(model, pool[0],
                       wait=True).result(timeout=600).probs
    pS = sharded.submit(model, pool[0],
                        wait=True).result(timeout=600).probs
    bitwise = bool(np.array_equal(np.asarray(p1), np.asarray(pS)))

    def measure(srv):
        t0 = time.perf_counter()
        futs = srv.submit_many(model, reqs, wait=True)
        lat = [f.result(timeout=600).total_ms for f in futs]
        return n_requests / (time.perf_counter() - t0), lat

    qps1, qpsS, lat1, latS = [], [], [], []
    try:
        for _ in range(max(1, int(rounds))):
            q, l = measure(single)
            qps1.append(q)
            lat1 += l
            q, l = measure(sharded)
            qpsS.append(q)
            latS += l
        post_warmup = lmS.replicas[0].compile_count() - warm_compiles
    finally:
        single.close(drain=True)
        sharded.close(drain=True)
    q1 = float(np.median(qps1))
    qS = float(np.median(qpsS))
    out = {"serving_sharded_model": model,
           "serving_sharded_shards": lmS.replicas[0].shards,
           "serving_sharded_topology": _serving_topology(devs),
           "serving_sharded_rounds": int(rounds),
           "serving_sharded_n_requests": int(n_requests),
           "serving_sharded_qps": round(qS, 1),
           "serving_sharded_p50_ms": round(
               float(np.percentile(latS, 50)), 3),
           "serving_sharded_p99_ms": round(
               float(np.percentile(latS, 99)), 3),
           "serving_sharded_single_qps": round(q1, 1),
           "serving_sharded_single_p50_ms": round(
               float(np.percentile(lat1, 50)), 3),
           "serving_sharded_single_p99_ms": round(
               float(np.percentile(lat1, 99)), 3),
           "serving_sharded_ratio": round(qS / q1, 3) if q1 else None,
           "serving_sharded_bitwise": bitwise,
           "serving_sharded_post_warmup_compiles": int(post_warmup)}
    log(json.dumps(out))
    return out


def bench_elastic(rounds: int = 6):
    """Elastic-runtime straggler A/B via `scripts/chaos_run.py --ab` in a
    subprocess: the same seeded fault plan (one persistent 20× straggler,
    one crash + snapshot-catch-up join) under the full barrier vs
    partial-quorum averaging, compared on SIMULATED stall-seconds from
    round telemetry — deterministic, no wall-clock in the verdict.

    A subprocess because the scenario needs the 8-device virtual CPU
    mesh (`--xla_force_host_platform_device_count=8`), and this process
    has already initialised its backend; re-raises on a non-zero exit or
    a malformed line so the guarded leg in _run_legs omits the fields."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "chaos_run.py")
    proc = subprocess.run(
        [sys.executable, script, "--ab", "--proc", "--rounds",
         str(rounds)],
        capture_output=True, text=True, env=env, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"chaos_run.py exited {proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}")
    # chaos_run prints ONE JSON line on stdout (same contract as bench)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    if not rec.get("ok"):
        raise RuntimeError(f"chaos_run.py reported not-ok: {rec}")
    out = {"elastic_workers": rec["workers"],
           "elastic_rounds": rec["rounds"],
           "elastic_joins": rec["joins"],
           "elastic_crashes": rec["crashes"],
           "elastic_tau_final": rec["tau_final"],
           "elastic_full_barrier_stall_s": rec["full_barrier_stall_s"],
           "elastic_quorum_stall_s": rec["partial_quorum_stall_s"],
           "elastic_stall_ratio": rec["stall_ratio"],
           # process-level arm (schema v4): REAL worker subprocesses,
           # seeded SIGKILL + manifest-validated snapshot catch-up join
           "elastic_proc_workers": rec["proc_workers"],
           "elastic_proc_rounds": rec["proc_rounds"],
           "elastic_proc_quorums": rec["proc_quorums"],
           "elastic_proc_crashes": int(rec["proc_crashes"]),
           "elastic_proc_restarts": int(rec["proc_restarts"]),
           "elastic_proc_join_source": rec["proc_join_source"],
           "elastic_proc_torn_skipped": rec["proc_torn_skipped"]}
    log(json.dumps(out))
    return out


def bench_trainserve():
    """Train-while-serve loop via `scripts/trainserve_run.py --smoke` in
    a subprocess: a lenet trainer subprocess publishing gated snapshot
    generations, a live InferenceServer under seeded open-loop load, and
    the PromotionWatcher hot-swapping each promoted generation into the
    replica set — the record carries promotions, staleness mean/max,
    the swap-induced p99 delta, and the zero-drop bar (dropped must be
    0 across generation swaps or the leg raises).

    A subprocess because the trainer itself is a subprocess and the
    scenario wants a clean CPU backend; re-raises on a non-zero exit or
    a not-ok line so the guarded leg in _run_legs omits the fields."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "trainserve_run.py")
    proc = subprocess.run(
        [sys.executable, script, "--smoke", "--corrupt_at", "1"],
        capture_output=True, text=True, env=env, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"trainserve_run.py exited {proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}")
    # trainserve_run prints ONE JSON line on stdout (chaos_run contract)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    if not rec.get("ok"):
        raise RuntimeError(f"trainserve_run.py reported not-ok: {rec}")
    if rec.get("dropped"):
        raise RuntimeError(
            f"trainserve dropped {rec['dropped']} requests across "
            f"generation swaps: {rec}")
    out = {"trainserve_promotions": int(rec["promotions"]),
           "trainserve_rejections": int(rec["rejections"]),
           "trainserve_staleness_mean": rec["staleness_mean"],
           "trainserve_staleness_max": rec["staleness_max"],
           "trainserve_swap_p99_delta_ms": rec["swap_p99_delta_ms"],
           "trainserve_dropped": int(rec["dropped"]),
           "trainserve_completed": int(rec["completed"]),
           "trainserve_generations": int(rec["generations"]),
           "trainserve_agreement_mean": rec["agreement_mean"],
           "trainserve_traffic_records": int(rec["traffic_records"])}
    log(json.dumps(out))
    return out


def bench_serving_resilience():
    """Serving degradation drill via `scripts/serve_chaos_run.py --smoke`
    in a subprocess: a seeded ServeFaultPlan (replica error-storm + hard
    kill + latency spikes) under flash-crowd load against a live
    3-replica server with the resilience control plane armed — the
    record carries breaker trips/respawns, recovery time, sheds (batch
    only), deadline drops, interactive p99, and the exactly-once bar
    (dropped must be 0 or the leg raises; the smoke itself also asserts
    bitwise fault-schedule replay and single-generation responses).

    A subprocess for a clean CPU backend and because the smoke's exit
    code IS the pass/fail signal; re-raises on a non-zero exit or a
    not-ok line so the guarded leg in _run_legs omits the fields."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "serve_chaos_run.py")
    proc = subprocess.run(
        [sys.executable, script, "--smoke"],
        capture_output=True, text=True, env=env, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"serve_chaos_run.py exited {proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}")
    # serve_chaos_run prints ONE JSON line on stdout (chaos_run contract)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    if not rec.get("ok"):
        raise RuntimeError(f"serve_chaos_run.py reported not-ok: {rec}")
    if rec.get("dropped"):
        raise RuntimeError(
            f"serve chaos dropped {rec['dropped']} requests (every "
            f"request must be answered exactly once): {rec}")
    out = {"serving_resilience_requests": int(rec["requests"]),
           "serving_resilience_completed": int(rec["completed"]),
           "serving_resilience_dropped": int(rec["dropped"]),
           "serving_resilience_sheds": int(rec["sheds"]),
           "serving_resilience_deadline_drops": int(
               rec["deadline_drops"]),
           "serving_resilience_breaker_trips": int(rec["breaker_trips"]),
           "serving_resilience_respawns": int(rec["respawns"]),
           "serving_resilience_recovery_s": rec["recovery_s"],
           "serving_resilience_interactive_p99_ms": rec[
               "interactive_p99_ms"],
           "serving_resilience_replay_bitwise": bool(
               rec["replay_bitwise"])}
    log(json.dumps(out))
    return out


def bench_serving_autoscale():
    """Autoscaling drill via `scripts/autoscale_drill.py --smoke` in a
    subprocess: diurnal / spike / flash-crowd load phases against a
    live server with the SLO-driven autoscaler armed over a 3-slot
    pool — the record carries scale-up/scale-down counts, the converged
    per-phase p99 band, the errstorm doom-loop bar (breaker trips with
    ZERO scale-ups during the outage), and the exactly-once bar
    (dropped must be 0 or the leg raises; the smoke itself also asserts
    the floor, placer-routed scale-ups, and bitwise policy-schedule
    replay).

    A subprocess for a clean CPU backend and because the smoke's exit
    code IS the pass/fail signal; re-raises on a non-zero exit or a
    not-ok line so the guarded leg in _run_legs omits the fields."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "autoscale_drill.py")
    proc = subprocess.run(
        [sys.executable, script, "--smoke"],
        capture_output=True, text=True, env=env, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"autoscale_drill.py exited {proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}")
    # autoscale_drill prints ONE JSON line on stdout (chaos_run contract)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    if not rec.get("ok"):
        raise RuntimeError(f"autoscale_drill.py reported not-ok: {rec}")
    if rec.get("dropped"):
        raise RuntimeError(
            f"autoscale drill dropped {rec['dropped']} requests (every "
            f"request must be answered exactly once): {rec}")
    out = {"serving_autoscale_pool": int(rec["pool"]),
           "serving_autoscale_ups": int(rec["ups"]),
           "serving_autoscale_downs": int(rec["downs"]),
           "serving_autoscale_min_active": int(rec["min_active"]),
           "serving_autoscale_max_active": int(rec["max_active"]),
           "serving_autoscale_dropped": int(rec["dropped"]),
           "serving_autoscale_completed": int(rec["completed"]),
           "serving_autoscale_tail_p99_ms": max(
               p["tail_p99_ms"] for p in rec["phases"]),
           "serving_autoscale_storm_trips": int(
               rec["storm"]["breaker_trips"]),
           "serving_autoscale_storm_ups_during_outage": int(
               rec["storm"]["ups_during_outage"]),
           "serving_autoscale_replay_bitwise": bool(
               rec["replay_bitwise"])}
    log(json.dumps(out))
    return out


def bench_serving_fleet():
    """Fleet-vs-in-process serving A/B via `scripts/fleet_bench.py
    --smoke` in a subprocess: interleaved closed bursts through the
    OS-process fleet router (serving/fleet.py) and through the plain
    in-process server at the same replica count — the record carries
    both arms' median QPS + pooled p50/p99, the speedup ratio (an
    honest wash or deficit on one contended core: the leg prices the
    IPC tax, the chaos drill prices the isolation win), and the
    zero-restart bar (dropped must be 0 or the leg raises; the smoke
    itself also asserts bitwise A/B parity across the process
    boundary).

    A subprocess for a clean CPU backend and because the smoke's exit
    code IS the pass/fail signal; re-raises on a non-zero exit or a
    not-ok line so the guarded leg in _run_legs omits the fields."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "fleet_bench.py")
    proc = subprocess.run(
        [sys.executable, script, "--smoke"],
        capture_output=True, text=True, env=env, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"fleet_bench.py exited {proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}")
    # fleet_bench prints ONE JSON line on stdout (chaos_run contract)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    if not rec.get("ok"):
        raise RuntimeError(f"fleet_bench.py reported not-ok: {rec}")
    if rec.get("dropped"):
        raise RuntimeError(
            f"fleet bench dropped {rec['dropped']} requests (every "
            f"request must be answered exactly once): {rec}")
    out = {"serving_fleet_workers": int(rec["workers"]),
           "serving_fleet_qps": rec["fleet_qps"],
           "serving_fleet_single_qps": rec["single_qps"],
           "serving_fleet_speedup": rec["speedup"],
           "serving_fleet_p50_ms": rec["fleet_p50_ms"],
           "serving_fleet_p99_ms": rec["fleet_p99_ms"],
           "serving_fleet_dropped": int(rec["dropped"]),
           "serving_fleet_restarts": int(rec["worker_restarts"]),
           "serving_fleet_parity_failed": int(rec["parity_failed"])}
    log(json.dumps(out))
    return out


def bench_serving_compound():
    """Compound-serving drill via `scripts/serve_chaos_run.py --smoke
    --compound` in a subprocess: a mixed seeded burst of windowed-
    detection compounds, featurization compounds, and plain classify
    rows against three lanes of one faulted server
    (serving/compound.py) — the record carries the zero-partial /
    exactly-once bars, whole-request batch sheds (interactive sheds
    must be 0), interactive p99, and the interleaved served-vs-offline
    A/B medians with the bitwise parity bar (dropped or a partial
    response raises so the guarded leg omits the fields; the smoke
    itself also asserts event-stream reconciliation and bitwise
    fault-schedule replay).

    A subprocess for a clean CPU backend and because the smoke's exit
    code IS the pass/fail signal; re-raises on a non-zero exit or a
    not-ok line so the guarded leg in _run_legs omits the fields."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "serve_chaos_run.py")
    proc = subprocess.run(
        [sys.executable, script, "--smoke", "--compound",
         "--requests", "120", "--qps", "200"],
        capture_output=True, text=True, env=env, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"serve_chaos_run.py --compound exited {proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}")
    # serve_chaos_run prints ONE JSON line on stdout (chaos_run contract)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    if not rec.get("ok"):
        raise RuntimeError(
            f"serve_chaos_run.py --compound reported not-ok: {rec}")
    if rec.get("dropped") or rec.get("partial_responses"):
        raise RuntimeError(
            f"compound drill dropped {rec.get('dropped')} / answered "
            f"{rec.get('partial_responses')} partial compounds (every "
            f"logical request must be answered exactly once, whole or "
            f"not at all): {rec}")
    out = {"serving_compound_requests": int(rec["requests"]),
           "serving_compound_completed": int(rec["completed_compound"]),
           "serving_compound_dropped": int(rec["dropped"]),
           "serving_compound_partials": int(rec["partial_responses"]),
           "serving_compound_sheds": int(rec["sheds"]),
           "serving_compound_sheds_interactive": int(
               rec["sheds_interactive"]),
           "serving_compound_breaker_trips": int(rec["breaker_trips"]),
           "serving_compound_interactive_p99_ms": rec[
               "interactive_p99_ms"],
           "serving_compound_ab_served_ms": rec["ab_served_ms"],
           "serving_compound_ab_offline_ms": rec["ab_offline_ms"],
           "serving_compound_parity_failed": int(rec["parity_failed"]),
           "serving_compound_replay_bitwise": bool(
               rec["replay_bitwise"])}
    log(json.dumps(out))
    return out


def bench_longctx_lm(seq_len: int = 16384, n_layers: int = 4,
                     d_model: int = 512, heads: int = 8,
                     block: int = 1024):
    """Long-context LM training throughput on one chip: full update steps
    (fwd+bwd+momentum, bf16 compute) of the canonical causal transformer
    with remat'd blockwise attention — the driver-tracked proof that the
    long-context path stays healthy.  No reference counterpart (SURVEY.md
    §5.7: the reference has no sequence dimension); scaling table to
    S=65k in BENCH_NOTES.md."""
    import functools

    import jax
    import jax.numpy as jnp

    from sparknet_tpu.parallel.seq_parallel import tiny_transformer
    from sparknet_tpu.proto.caffe_pb import SolverParameter
    from sparknet_tpu.solver import updates as U
    from sparknet_tpu.solver.solver import make_update_fn
    from sparknet_tpu.utils.timers import differenced_chain_s

    sp = SolverParameter()
    sp.msg.set("base_lr", 0.01)
    sp.msg.set("lr_policy", "fixed")
    sp.msg.set("momentum", 0.9)
    init, apply_fn = tiny_transformer(n_layers, 256, d_model, heads,
                                      max_seq=seq_len, attn_block=block)
    params = {k: jnp.asarray(v) for k, v in init(0).items()}
    state = U.init_state(params, sp.resolved_type())
    ones = {k: 1.0 for k in params}
    upd = make_update_fn(None, sp, lr_mults=ones, decay_mults=ones)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 256, (1, seq_len)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)

    def loss_fn(p, toks):
        p = {k: v.astype(jnp.bfloat16) for k, v in p.items()}
        logits = apply_fn(p, toks).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, tgts[..., None], -1).mean()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, st, it, toks):
        l, g = jax.value_and_grad(loss_fn)(p, toks)
        p2, st2 = upd(p, st, g, it)
        return p2, st2, l

    ps = [params, state]
    it = [0]

    def run(m):
        t0 = time.perf_counter()
        l = None
        for _ in range(m):
            ps[0], ps[1], l = step(ps[0], ps[1], jnp.int32(it[0]), toks)
            it[0] += 1
        float(l)
        return time.perf_counter() - t0

    s = differenced_chain_s(run, 8)
    out = {"longctx_seq_len": seq_len,
           "longctx_lm_tok_per_sec": round(seq_len / s, 1)}
    log(json.dumps(out))
    return out


def ensure_native_jpeg() -> None:
    """Build + verify the libjpeg pool — silently falling back to the
    PIL path would measure the wrong tier.  Build/toolchain failures
    surface as ONE "native jpeg" RuntimeError shape so every caller
    (main's guard, the CI skip, scripts/ingest_probe.py) handles the
    same error."""
    import subprocess

    native_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "native")
    try:
        subprocess.run(["make", "-s", "all"], cwd=native_dir, check=True)
    except (subprocess.CalledProcessError, FileNotFoundError, OSError) as e:
        raise RuntimeError(f"native jpeg tier build failed: {e}") from e
    from sparknet_tpu.data import native_jpeg

    if not native_jpeg.available():
        raise RuntimeError("native jpeg decoder unavailable after build — "
                           "refusing to bench the fallback path as native")


def bench_imagenet_native(rounds: int = 3, tau: int = 5, batch: int = 64,
                          size: int = 256, crop: int = 227,
                          n_imgs: int = 512, n_shards: int = 2,
                          model: str = "alexnet") -> dict:
    """Sustained ImageNet-SHAPE training throughput through the NATIVE
    data tier: synthetic-JPEG tar shards -> ImageNetLoader ->
    native/jpeg_decoder.cpp thread pool (data/scale_convert.convert_stream
    picks it up when built) -> raw uint8 feed -> crop/mirror/mean fused
    into the compiled round (device_transform) with one-round-ahead
    prefetch.  This is the C++ tier measured in the driver record, not
    only claimed in tests (VERDICT r3 item 8; reference analogue:
    preprocessing/ScaleAndConvert.scala:16-27 + base_data_layer.cpp
    prefetch feeding the solver loop)."""
    import shutil
    import tempfile

    import numpy as np

    ensure_native_jpeg()

    from sparknet_tpu.apps.imagenet_app import build_solver
    from sparknet_tpu.data.imagenet import (ImageNetLoader,
                                            write_synthetic_jpeg_shards)

    tmp = tempfile.mkdtemp(prefix="sparknet_bench_imgnet_")
    try:
        shard_paths, label_file = write_synthetic_jpeg_shards(
            tmp, n_imgs=n_imgs, n_shards=n_shards, size=size, seed=0)

        mean = np.full((3, size, size), 128.0, np.float32)
        solver = build_solver(model, 1, tau, batch, batch, crop=crop,
                              mean_image=mean, device_transform=True)
        loader = ImageNetLoader(tmp)

        class JpegStream:
            # cycling raw-uint8 stream off the tar shards; stream_safe by
            # construction, so prefetch staging one round ahead is exact
            stream_safe = True

            def __init__(self):
                self._it = None

            def _fresh(self):
                return loader.batches(label_file, batch_size=batch,
                                      height=size, width=size,
                                      shards=shard_paths)

            def __call__(self):
                if self._it is None:
                    self._it = self._fresh()
                try:
                    imgs, labels = next(self._it)
                except StopIteration:
                    self._it = self._fresh()
                    imgs, labels = next(self._it)
                return {"data": imgs, "label": labels}

        solver.set_train_data([JpegStream()])
        solver.set_prefetch(True)
        solver.run_round()  # compile + warm
        solver.reset_ingest_stats()  # count only the measured window
        solver.reset_round_stats()
        t0 = time.perf_counter()
        for r in range(rounds):
            solver.run_round(prefetch_next=r < rounds - 1)
        dt = time.perf_counter() - t0
        ingest = solver.ingest_stats()
        telemetry = {k: v for k, v in solver.round_stats().items()
                     if k != "per_round"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    from sparknet_tpu.ops.fused_block import effective_fused_blocks_mode

    out = {"imagenet_native_fed_imgs_per_sec":
           round(rounds * tau * batch / dt, 1),
           "imagenet_native_batch": batch, "imagenet_native_tau": tau,
           "imagenet_native_precision": solver.precision,
           "imagenet_native_fused_blocks": effective_fused_blocks_mode(),
           "imagenet_native_ingest": ingest,
           "imagenet_native_round_telemetry": telemetry}
    log(json.dumps(out))
    return out


def bench_cifar_e2e(rounds: int = 6, tau: int = 100,
                    prefetch: bool = True) -> dict:
    """Sustained HOST-FED CIFAR training throughput, prefetch on — the
    one honest end-to-end figure this box resolves (small batches
    amortize the tunnel's per-RPC floor; ACCURACY.md measured 1,214 img/s
    on this path).  Emitting it as a driver-tracked field makes feed-path
    regressions visible in BENCH_r* records (VERDICT r2 item 7).

    Shape of the run: the reference cifar10_quick recipe (batch 100) as
    one τ-step compiled round per device call, fed by a round-agnostic
    host stream (so set_prefetch's depth-k look-ahead is safe), fresh
    batches pulled and shipped every round.  Returns
    {"imgs_per_sec": ..., "ingest": solver.ingest_stats(),
    "round_telemetry": solver.round_stats() sans per_round} so the
    per-stage pull/stack/device_put/stall split AND the per-round phase
    means ride the driver record (data/counters.py + parallel/dist.py
    round telemetry semantics).  `precision` and `fused_blocks` (the
    EFFECTIVE fused-blocks mode — pallas degrades to xla off-TPU) stamp
    the record so A/B runs are attributable."""
    import numpy as np

    from sparknet_tpu.apps.cifar_app import build_solver

    batch = 100  # the reference cifar10_quick batch; ties feed + formula
    solver = build_solver("quick", 1, tau, batch_size=batch)
    rng = np.random.RandomState(0)
    pool_x = rng.randint(0, 256, size=(10000, 3, 32, 32)).astype(np.uint8)
    pool_y = rng.randint(0, 10, size=10000).astype(np.int32)
    mean = pool_x.mean(axis=0).astype(np.float32)

    class StreamFeed:
        # cycling host stream; stream_safe by construction (no per-round
        # window), so prefetch staging one round ahead is exact
        stream_safe = True

        def __init__(self):
            self.i = 0

        def __call__(self):
            sel = (np.arange(batch) + self.i * batch) % len(pool_y)
            self.i += 1
            return {"data": pool_x[sel].astype(np.float32) - mean,
                    "label": pool_y[sel]}

    solver.set_train_data([StreamFeed()])
    solver.set_prefetch(prefetch)  # scripts/prefetch_delta.py flips this
    solver.run_round()  # compile + warm
    solver.reset_ingest_stats()  # count only the measured window
    solver.reset_round_stats()
    t0 = time.perf_counter()
    for r in range(rounds):
        solver.run_round(prefetch_next=r < rounds - 1)
    dt = time.perf_counter() - t0
    from sparknet_tpu.ops.fused_block import effective_fused_blocks_mode

    return {"imgs_per_sec": rounds * tau * batch / dt,
            "precision": solver.precision,
            "fused_blocks": effective_fused_blocks_mode(),
            "ingest": solver.ingest_stats(),
            "round_telemetry": {k: v for k, v
                                in solver.round_stats().items()
                                if k != "per_round"}}


LAST_GOOD = os.environ.get(
    "SPARKNET_BENCH_LAST_GOOD",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_LAST_GOOD.json"))

# set True once a JSON line (fresh or stale) has reached stdout, so the
# signal bail-out never double-prints and never clobbers a fresh record
_json_line_emitted = False


# every field a current bench build can emit; the per-leg merge prunes
# keys outside this set so renamed-away metrics from old records cannot
# ghost through stale replays forever
_KNOWN_FIELDS = {
    "metric", "value", "unit", "vs_baseline", "leg_utc",
    "mfu", "fused_transform_imgs_per_sec", "host_fed_imgs_per_sec",
    "wire_mbps_post_exec",
    "googlenet_imgs_per_sec", "googlenet_fused_transform_imgs_per_sec",
    "googlenet_mfu", "googlenet_b128_imgs_per_sec", "googlenet_b128_mfu",
    "alexnet_infer_imgs_per_sec", "googlenet_infer_imgs_per_sec",
    "longctx_lm_tok_per_sec", "cifar_e2e_imgs_per_sec",
    "cifar_e2e_ingest", "cifar_e2e_round_telemetry",
    # attribution stamps (schema v7): precision + the EFFECTIVE
    # fused-blocks mode (pallas degrades to xla off-TPU) on the two
    # end-to-end training legs, so A/B records name what actually ran
    "cifar_e2e_precision", "cifar_e2e_fused_blocks",
    "imagenet_native_fed_imgs_per_sec", "imagenet_native_batch",
    "imagenet_native_tau", "imagenet_native_ingest",
    "imagenet_native_round_telemetry",
    "imagenet_native_precision", "imagenet_native_fused_blocks",
    # emit-time provenance stamps (_stamp); never persisted by
    # _persist_leg, listed so a hand-edited record carrying them is
    # not flagged as drift
    "schema_version", "git_sha", "env",
    "serving_model", "serving_offered_qps", "serving_qps",
    "serving_p50_ms", "serving_p99_ms", "serving_batch_occupancy",
    "serving_rejected", "serving_compiles",
    "serving_int8_model", "serving_int8_offered_qps", "serving_int8_qps",
    "serving_int8_p50_ms", "serving_int8_p99_ms",
    "serving_int8_batch_occupancy", "serving_int8_rejected",
    "serving_int8_compiles", "serving_int8_agreement",
    "serving_int8_param_bytes",
    # mesh-serving stamps (schema v3): every serving record carries its
    # replica count + device topology; the serving_mesh leg lands the
    # interleaved single-vs-mesh A/B
    "serving_replicas", "serving_topology",
    "serving_int8_replicas", "serving_int8_topology",
    "serving_mesh_model", "serving_mesh_replicas",
    "serving_mesh_topology", "serving_mesh_rounds",
    "serving_mesh_n_requests", "serving_mesh_qps",
    "serving_mesh_p50_ms", "serving_mesh_p99_ms",
    "serving_single_qps", "serving_single_p50_ms", "serving_single_p99_ms",
    "serving_mesh_speedup", "serving_mesh_compiles",
    # sharded-serving A/B (schema v8): one gspmd slice replica vs one
    # single-device replica, plus the bitwise and zero-recompile bars
    "serving_sharded_model", "serving_sharded_shards",
    "serving_sharded_topology", "serving_sharded_rounds",
    "serving_sharded_n_requests", "serving_sharded_qps",
    "serving_sharded_p50_ms", "serving_sharded_p99_ms",
    "serving_sharded_single_qps", "serving_sharded_single_p50_ms",
    "serving_sharded_single_p99_ms", "serving_sharded_ratio",
    "serving_sharded_bitwise", "serving_sharded_post_warmup_compiles",
    # elastic-runtime straggler A/B (simulated stall-seconds, chaos_run
    # subprocess on the 8-device virtual CPU mesh)
    "elastic_workers", "elastic_rounds", "elastic_joins",
    "elastic_crashes", "elastic_tau_final",
    "elastic_full_barrier_stall_s", "elastic_quorum_stall_s",
    "elastic_stall_ratio",
    # process-level elastic arm (schema v4): real subprocess workers,
    # SIGKILL chaos, snapshot catch-up join
    "elastic_proc_workers", "elastic_proc_rounds",
    "elastic_proc_quorums", "elastic_proc_crashes",
    "elastic_proc_restarts", "elastic_proc_join_source",
    "elastic_proc_torn_skipped",
    # train-while-serve loop (schema v5): live trainer subprocess +
    # promotion watcher + served-traffic capture, zero-drop bar
    "trainserve_promotions", "trainserve_rejections",
    "trainserve_staleness_mean", "trainserve_staleness_max",
    "trainserve_swap_p99_delta_ms", "trainserve_dropped",
    "trainserve_completed", "trainserve_generations",
    "trainserve_agreement_mean", "trainserve_traffic_records",
    # serving resilience drill (schema v6): seeded replica chaos under
    # flash-crowd load — breaker trips, respawns, sheds, zero-drop bar
    "serving_resilience_requests", "serving_resilience_completed",
    "serving_resilience_dropped", "serving_resilience_sheds",
    "serving_resilience_deadline_drops",
    "serving_resilience_breaker_trips", "serving_resilience_respawns",
    "serving_resilience_recovery_s",
    "serving_resilience_interactive_p99_ms",
    "serving_resilience_replay_bitwise",
    # serving autoscale drill (schema v9): shaped load grows/shrinks
    # the replica set through the placer; errstorm doom-loop bar
    "serving_autoscale_pool", "serving_autoscale_ups",
    "serving_autoscale_downs", "serving_autoscale_min_active",
    "serving_autoscale_max_active", "serving_autoscale_dropped",
    "serving_autoscale_completed", "serving_autoscale_tail_p99_ms",
    "serving_autoscale_storm_trips",
    "serving_autoscale_storm_ups_during_outage",
    "serving_autoscale_replay_bitwise",
    # fleet serving A/B (schema v10): OS-process workers behind the
    # router vs the in-process server at the same replica count —
    # honest-wash QPS arms, the IPC-tax ratio, and the zero-restart /
    # bitwise-parity bars from fleet_bench.py --smoke
    "serving_fleet_workers", "serving_fleet_qps",
    "serving_fleet_single_qps", "serving_fleet_speedup",
    "serving_fleet_p50_ms", "serving_fleet_p99_ms",
    "serving_fleet_dropped", "serving_fleet_restarts",
    "serving_fleet_parity_failed",
    # compound serving (schema v11): windowed detection + featurization
    # as served workloads — zero-partial / exactly-once / whole-request
    # shed bars and the interleaved served-vs-offline A/B medians with
    # bitwise parity, from serve_chaos_run.py --smoke --compound
    "serving_compound_requests", "serving_compound_completed",
    "serving_compound_dropped", "serving_compound_partials",
    "serving_compound_sheds", "serving_compound_sheds_interactive",
    "serving_compound_breaker_trips",
    "serving_compound_interactive_p99_ms",
    "serving_compound_ab_served_ms", "serving_compound_ab_offline_ms",
    "serving_compound_parity_failed",
    "serving_compound_replay_bitwise",
}

# every leg name main() lands; leg_utc stamps outside this set (renamed
# legs) are pruned on merge so a stale replay never advertises freshness
# for data that no longer exists
_KNOWN_LEGS = {
    "alexnet_train", "googlenet_train_b64", "googlenet_train_b128",
    "alexnet_infer", "googlenet_infer", "longctx_lm", "cifar_e2e",
    "imagenet_native", "serving", "serving_int8", "serving_mesh",
    "serving_sharded", "elastic", "trainserve", "serving_resilience",
    "serving_autoscale", "serving_fleet", "serving_compound",
}


# fields landed by legs of THIS process, so later merges never prune a
# sibling leg's same-run data even when _KNOWN_FIELDS lags behind
_session_fields: set = set()


def _persist_leg(leg: str, fields: dict) -> None:
    """Merge ONE completed leg's fields into the last-good record on disk
    immediately (VERDICT r4 item 1: a wedge mid-chain must stale only the
    legs not yet run, not the whole record).  Each merge stamps the leg in
    `leg_utc`, so a later stale replay shows per-leg freshness; any prior
    stale flag is cleared because the record now carries fresh data."""
    try:
        try:
            cur = json.load(open(LAST_GOOD))
        except (OSError, ValueError):
            cur = {}
        if not isinstance(cur, dict):  # truncated/hand-edited record
            cur = {}
        unknown = set(fields) - _KNOWN_FIELDS
        if unknown:  # drift alarm: a new land() metric self-registers
            # while being emitted, but update _KNOWN_FIELDS or it will
            # be pruned from replays by runs that die before its leg
            log(f"_persist_leg: fields not in _KNOWN_FIELDS: "
                f"{sorted(unknown)} — update the allowlist")
        _session_fields.update(fields)
        # everything landed THIS run survives later legs' merges even if
        # the allowlist is stale; only cross-run ghosts get pruned
        keep = _KNOWN_FIELDS | _session_fields
        cur = {k: v for k, v in cur.items() if k in keep}
        # contract keys must exist even if the chain dies before the
        # alexnet leg would set them (a partial record on a fresh
        # checkout still replays as a well-formed line)
        cur.setdefault("metric", "alexnet_train_imgs_per_sec")
        cur.setdefault("unit", "img/s")
        cur.setdefault("value", None)
        cur.setdefault("vs_baseline", None)
        cur.update(fields)
        utc = cur.get("leg_utc")
        if not isinstance(utc, dict):
            utc = {}
        utc = {k: v for k, v in utc.items() if k in _KNOWN_LEGS}
        utc[leg] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        cur["leg_utc"] = utc
        tmp = LAST_GOOD + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cur, f)
        os.replace(tmp, LAST_GOOD)
    except Exception as e:
        # persistence must never break the ONE-JSON-line contract: the
        # in-flight result dict still carries every landed field
        log(f"could not persist leg {leg}: {e!r}")


def _stale_record(reason: str) -> dict:
    """The most recent good measurement, loudly flagged as stale; if no
    last-good record is readable, the COMMITTED seed reconstruction
    (BENCH_LAST_GOOD_SEED.json — box reboots wipe the gitignored
    last-good file, round-5 lesson) and only then a minimal-but-parseable
    placeholder so the ONE-JSON-line contract survives a fresh checkout."""
    seed = os.environ.get(
        "SPARKNET_BENCH_SEED",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_LAST_GOOD_SEED.json"))
    stale = None
    for path in (LAST_GOOD, seed):
        try:
            stale = json.load(open(path))
            break
        except (OSError, ValueError):
            continue
    if stale is None:
        stale = {"metric": "alexnet_train_imgs_per_sec", "value": None,
                 "unit": "img/s", "vs_baseline": None,
                 "no_last_good_record": True}
    stale["stale_due_to_unreachable_tpu"] = True
    stale["stale_reason"] = reason
    return stale


BENCH_SCHEMA_VERSION = 11  # v11: serving_compound leg (compound
#                           serving drill — mixed windowed-detection /
#                           featurization / classify burst under
#                           seeded faults; zero-partial, exactly-once
#                           and whole-request-shed bars, interleaved
#                           served-vs-offline A/B medians with bitwise
#                           parity; serve_chaos_run.py --compound
#                           subprocess);
#                           v10: serving_fleet leg (OS-process fleet
#                           router vs in-process server, interleaved
#                           closed bursts — both arms' median QPS +
#                           p50/p99, speedup ratio, zero-drop /
#                           zero-restart / bitwise cross-process
#                           parity bars; fleet_bench.py subprocess);
#                           v9: serving_autoscale leg (autoscaling
#                           drill — scale-up/down counts through the
#                           placer, converged tail p99, errstorm
#                           doom-loop bar (zero ups during the outage),
#                           dropped==0 bar, bitwise policy replay;
#                           autoscale_drill.py subprocess);
#                           v8: serving_sharded leg (gspmd slice replica
#                           vs single-device A/B — serving_sharded_*
#                           QPS/latency, ratio, bitwise bar,
#                           post-warmup-compiles==0 bar);
#                           v7: cifar_e2e/imagenet_native records carry
#                           precision + effective fused-blocks stamps
#                           (cifar_e2e_precision, cifar_e2e_fused_blocks,
#                           imagenet_native_precision,
#                           imagenet_native_fused_blocks) so full-block
#                           A/B runs are attributable;
#                           v6: serving_resilience leg (degradation
#                           drill — breaker trips/respawns, recovery_s,
#                           sheds, interactive p99, dropped==0 bar;
#                           serve_chaos_run.py subprocess);
#                           v5: trainserve leg (train-while-serve loop —
#                           promotions, staleness mean/max, swap p99
#                           delta, dropped==0 bar; trainserve_run.py
#                           subprocess);
#                           v4: elastic leg gains the process-level arm
#                           (elastic_proc_* — real subprocess workers,
#                           SIGKILL chaos, snapshot catch-up join);
#                           v3: serving replica/topology stamps + the
#                           serving_mesh interleaved A/B leg

# git SHA memo.  main() primes it up front (subprocess, once), so the
# signal bail handler — which must never reach a subprocess call — can
# stamp its fallback line from the memo alone (resolve=False below):
# a stale bail record carries the same provenance as a fresh one.
_git_sha_memo: list = []


def _stamp(payload: dict, resolve: bool = True) -> dict:
    """Provenance stamp applied at emit time: schema_version, the repo's
    short git SHA, and every active SPARKNET_* env knob, so a record line
    can be tied to the exact build + configuration that produced it.
    Stamps are NOT persisted by _persist_leg — a stale replay carries the
    replaying process's provenance, which is the honest reading (the env
    shown is the one that decided to replay).  `resolve=False` (the
    signal-handler path) never spawns the git subprocess: it reads the
    memo if primed and stamps git_sha null otherwise."""
    if not _git_sha_memo and resolve:
        sha = None
        try:
            import subprocess
            r = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, timeout=10)
            if r.returncode == 0:
                sha = r.stdout.decode().strip() or None
        except Exception:
            sha = None
        _git_sha_memo.append(sha)
    out = dict(payload)
    out["schema_version"] = BENCH_SCHEMA_VERSION
    out["git_sha"] = _git_sha_memo[0] if _git_sha_memo else None
    out["env"] = {k: os.environ[k] for k in sorted(os.environ)
                  if k.startswith("SPARKNET_")}
    return out


def _emit_json_line(payload: dict) -> None:
    """Write the ONE contract line with SIGTERM/SIGINT blocked across the
    check-write-flag critical section, so the bail handler can neither
    interleave with a fresh result nor double-print after a completed one.
    One unbuffered os.write keeps the line whole even if the process dies
    immediately after (print()'s buffer would be lost by os._exit)."""
    global _json_line_emitted
    import signal

    # stamp BEFORE masking: _stamp may spawn a subprocess (git), which
    # has no business inside the signal-masked critical section
    payload = _stamp(payload)

    mask = {signal.SIGTERM, signal.SIGINT}
    try:
        old = signal.pthread_sigmask(signal.SIG_BLOCK, mask)
    except (AttributeError, OSError):  # non-POSIX fallback: no masking
        old = None
    try:
        if _json_line_emitted:
            return
        os.write(1, (json.dumps(payload) + "\n").encode())
        _json_line_emitted = True
    finally:
        if old is not None:
            signal.pthread_sigmask(signal.SIG_SETMASK, old)


def _emit_stale(reason: str) -> None:
    if not _json_line_emitted:
        _emit_json_line(_stale_record(reason))


def _install_bail_handler() -> None:
    """Driver kill (SIGTERM) or ^C mid-wait/mid-bench must still produce
    one parseable JSON line: round 3 lost its driver record because the
    wait-for-health loop outlived the driver's timeout and died silently
    (VERDICT r3 weakness 1).  The handler avoids buffered Python I/O
    (reentrant BufferedWriter calls raise inside signal handlers) —
    os.write only — and the emit path masks these signals around its
    critical section, so the flag state it observes is never mid-write."""
    import signal

    def bail(signum, frame):
        global _json_line_emitted
        try:  # block the sibling signal too: a second handler entry at a
            # bytecode boundary between write and _exit would double-print
            signal.pthread_sigmask(signal.SIG_BLOCK,
                                   {signal.SIGTERM, signal.SIGINT})
        except (AttributeError, OSError):
            pass
        os.write(2, f"signal {signum}: emitting stale record "
                    f"before exit\n".encode())
        if not _json_line_emitted:
            _json_line_emitted = True
            try:
                # resolve=False: the memo main() primed, never a
                # subprocess — the stale bail line still carries
                # schema_version/git_sha/env like every other emit
                line = json.dumps(_stamp(_stale_record(
                    f"killed_by_signal_{signum}"), resolve=False)) + "\n"
            except Exception:
                line = ('{"metric": "alexnet_train_imgs_per_sec", '
                        '"value": null, "unit": "img/s", '
                        '"vs_baseline": null, '
                        f'"schema_version": {BENCH_SCHEMA_VERSION}, '
                        '"git_sha": null, '
                        '"stale_due_to_unreachable_tpu": true, '
                        f'"stale_reason": "killed_by_signal_{signum}"}}\n')
            os.write(1, line.encode())
        os._exit(0)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, bail)
        except (ValueError, OSError):  # non-main thread / exotic host
            pass


def _device_responsive(timeout_s: int = 240) -> bool:
    """Probe the accelerator in a subprocess with a hard timeout: the
    tunneled dev platform can wedge so that the first compile hangs
    forever (not an exception), which would hang the whole bench."""
    import subprocess

    if os.environ.get("SPARKNET_BENCH_FORCE_UNHEALTHY"):
        return False  # test hook: simulate a wedged tunnel deterministically

    code = ("import jax, jax.numpy as jnp;"
            "print(float(jax.jit(lambda a: (a @ a).sum())"
            "(jnp.ones((256, 256)))))")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           timeout=timeout_s, capture_output=True)
    except subprocess.TimeoutExpired:
        return False
    if r.returncode != 0:
        # fast deterministic failure is NOT the hang this guards against:
        # surface it instead of masking it behind a stale record
        sys.stderr.write(r.stderr.decode(errors="replace")[-2000:])
        raise SystemExit("device probe failed (not a hang); see stderr")
    return True


def main() -> None:
    from sparknet_tpu.utils.compile_cache import (apply_platform_env,
                                                  maybe_enable_compile_cache)

    _install_bail_handler()
    _stamp({})  # prime the git-SHA memo while no signal is in flight,
    # so a later bail() stamps the real SHA without a subprocess
    apply_platform_env()
    maybe_enable_compile_cache()

    # bounded wait-for-health: a TRANSIENT wedge should produce a
    # late-but-fresh measurement, not a stale replay (VERDICT r2 item 2).
    # Total patience and poll spacing are env-tunable for the driver.
    # Default budget sits WELL below the driver's observed kill timeout
    # (round 3 died ~16-20 min into a 3600s retry loop): better a stale
    # record than none.
    wait_budget = float(os.environ.get("SPARKNET_BENCH_WAIT_S", 900))
    poll_sleep = float(os.environ.get("SPARKNET_BENCH_POLL_SLEEP_S", 120))
    # the probe timeouts COUNT AGAINST the budget (clock starts here), so
    # a fully wedged tunnel reaches the stale emit in ~wait_budget seconds
    # — the handler is the backstop, not the plan
    deadline = time.time() + wait_budget
    healthy = _device_responsive(
        timeout_s=max(1, min(240, int(wait_budget))))
    while not healthy and time.time() < deadline:
        remain = deadline - time.time()
        log(f"device unresponsive; retrying for up to {int(remain)}s more "
            f"(SPARKNET_BENCH_WAIT_S={wait_budget:g})")
        time.sleep(min(poll_sleep, max(0.05, remain)))
        remain = deadline - time.time()
        if remain <= 0:
            break
        healthy = _device_responsive(
            timeout_s=max(1, min(120, int(remain) + 1)))

    if not healthy:
        # emit the most recent good measurement, loudly flagged — an
        # unreachable chip should degrade the record, not hang the driver
        log("DEVICE UNRESPONSIVE: emitting last good result as stale")
        _emit_stale("wait_budget_exhausted")
        return

    # each leg lands its fields into BOTH the in-flight result and the
    # on-disk last-good record the moment it completes, so a tunnel wedge
    # mid-chain (or a driver SIGTERM during a hung leg) stales only the
    # legs not yet run — the bail handler then replays a record whose
    # leg_utc stamps show exactly which legs are from this run
    result = {"metric": "alexnet_train_imgs_per_sec", "value": None,
              "unit": "img/s", "vs_baseline": None}

    def land(leg, fields):
        result.update(fields)
        _persist_leg(leg, fields)

    try:
        _run_legs(land)
    except Exception as e:
        # a leg that RAISES (tunnel RPC error surfacing as an exception
        # rather than a hang) must still honor the ONE-JSON-line
        # contract: replay the on-disk record OVERLAID with this run's
        # in-memory landed fields, so completed legs survive even when
        # _persist_leg itself could not write (disk full)
        log(f"bench leg raised, emitting last-good (with this run's "
            f"completed legs): {e!r}")
        rec = _stale_record(
            f"leg_exception: {type(e).__name__}: {str(e)[:200]}")
        rec.update({k: v for k, v in result.items() if v is not None})
        _emit_json_line(rec)
        return
    _emit_json_line(result)


def _run_legs(land) -> None:
    alex = bench_model(
        "alexnet", "/root/reference/caffe/models/bvlc_alexnet", 256, 227)
    land("alexnet_train", {
        "value": alex["device_resident_imgs_per_sec"],
        "vs_baseline": round(alex["device_resident_imgs_per_sec"]
                             / BASELINE_IMGS_PER_SEC, 2),
        "mfu": alex["mfu"],
        "fused_transform_imgs_per_sec":
            alex["fused_transform_imgs_per_sec"],
        "host_fed_imgs_per_sec": alex["host_fed_imgs_per_sec"],
        "wire_mbps_post_exec": alex["wire_mbps_post_exec"]})
    goog = bench_model(
        "googlenet", "/root/reference/caffe/models/bvlc_googlenet", 64, 224)
    land("googlenet_train_b64", {
        "googlenet_imgs_per_sec": goog["device_resident_imgs_per_sec"],
        "googlenet_fused_transform_imgs_per_sec":
            goog["fused_transform_imgs_per_sec"],
        "googlenet_mfu": goog["mfu"]})
    # b64 is the README-quoted parity config; b128 fills the chip better
    # (GOOGLENET_PROFILE.md) and rides along as a supplementary metric
    goog128 = bench_model(
        "googlenet", "/root/reference/caffe/models/bvlc_googlenet", 128,
        224)
    land("googlenet_train_b128", {
        "googlenet_b128_imgs_per_sec":
            goog128["device_resident_imgs_per_sec"],
        "googlenet_b128_mfu": goog128["mfu"]})
    # serving path (deploy forward, bf16) — reference: CaffeNet 50k val
    # in 60.7 s cuDNN = ~823 img/s (performance_hardware.md:19-24)
    alex_inf = bench_inference(
        "alexnet", "/root/reference/caffe/models/bvlc_alexnet", 256)
    land("alexnet_infer",
         {"alexnet_infer_imgs_per_sec": alex_inf["infer_imgs_per_sec"]})
    goog_inf = bench_inference(
        "googlenet", "/root/reference/caffe/models/bvlc_googlenet", 128)
    land("googlenet_infer",
         {"googlenet_infer_imgs_per_sec": goog_inf["infer_imgs_per_sec"]})
    longctx = bench_longctx_lm()
    land("longctx_lm",
         {"longctx_lm_tok_per_sec": longctx["longctx_lm_tok_per_sec"]})
    cifar_e2e = bench_cifar_e2e()
    log(json.dumps({"cifar_e2e_imgs_per_sec":
                    round(cifar_e2e["imgs_per_sec"], 1),
                    "cifar_e2e_ingest": cifar_e2e["ingest"]}))
    land("cifar_e2e", {"cifar_e2e_imgs_per_sec":
                       round(cifar_e2e["imgs_per_sec"], 1),
                       "cifar_e2e_precision": cifar_e2e["precision"],
                       "cifar_e2e_fused_blocks":
                       cifar_e2e["fused_blocks"],
                       "cifar_e2e_ingest": cifar_e2e["ingest"],
                       "cifar_e2e_round_telemetry":
                       cifar_e2e["round_telemetry"]})
    # online-serving leg (CPU backend by design — see bench_serving
    # docstring); guarded so a serving regression degrades one leg
    # rather than staling every device number already landed above
    try:
        serving = bench_serving()
    except Exception as e:
        log(f"serving leg failed, omitting its fields: {e!r}")
    else:
        land("serving", {k: serving[k] for k in (
            "serving_model", "serving_offered_qps", "serving_qps",
            "serving_p50_ms", "serving_p99_ms",
            "serving_batch_occupancy", "serving_rejected",
            "serving_compiles", "serving_replicas",
            "serving_topology")})
    # quantized serving leg (int8 w8a16, serving/quant.py): same offered
    # load through the packed-weight forward, plus the calibration top-1
    # agreement — latency AND fidelity ride the record together
    try:
        serving_q = bench_serving(quant="int8")
    except Exception as e:
        log(f"serving_int8 leg failed, omitting its fields: {e!r}")
    else:
        land("serving_int8", {k: serving_q[k] for k in (
            "serving_int8_qps", "serving_int8_p50_ms",
            "serving_int8_p99_ms", "serving_int8_batch_occupancy",
            "serving_int8_rejected", "serving_int8_compiles",
            "serving_int8_agreement", "serving_int8_param_bytes")})
    # mesh-serving A/B leg (CPU devices; replicas=0 -> one per device).
    # On a 1-device pool this degenerates to 1-vs-1 and says so in its
    # replica stamp — still landed, so the record shape is stable
    try:
        serving_m = bench_serving_mesh()
    except Exception as e:
        log(f"serving_mesh leg failed, omitting its fields: {e!r}")
    else:
        land("serving_mesh", {k: serving_m[k] for k in (
            "serving_mesh_model", "serving_mesh_replicas",
            "serving_mesh_topology", "serving_mesh_rounds",
            "serving_mesh_n_requests", "serving_mesh_qps",
            "serving_mesh_p50_ms", "serving_mesh_p99_ms",
            "serving_single_qps", "serving_single_p50_ms",
            "serving_single_p99_ms", "serving_mesh_speedup",
            "serving_mesh_compiles")})
    # sharded-serving A/B leg (CPU devices; one gspmd slice replica vs
    # one single-device replica, interleaved) — also lands the bitwise
    # and zero-recompile bars the sharded path promises
    try:
        serving_s = bench_serving_sharded()
    except Exception as e:
        log(f"serving_sharded leg failed, omitting its fields: {e!r}")
    else:
        land("serving_sharded", {k: serving_s[k] for k in (
            "serving_sharded_model", "serving_sharded_shards",
            "serving_sharded_topology", "serving_sharded_rounds",
            "serving_sharded_n_requests", "serving_sharded_qps",
            "serving_sharded_p50_ms", "serving_sharded_p99_ms",
            "serving_sharded_single_qps",
            "serving_sharded_single_p50_ms",
            "serving_sharded_single_p99_ms", "serving_sharded_ratio",
            "serving_sharded_bitwise",
            "serving_sharded_post_warmup_compiles")})
    # elastic straggler A/B (subprocess, virtual CPU mesh — see
    # bench_elastic docstring); guarded like the other CPU-path legs
    try:
        elastic = bench_elastic()
    except Exception as e:
        log(f"elastic leg failed, omitting its fields: {e!r}")
    else:
        land("elastic", {k: elastic[k] for k in (
            "elastic_workers", "elastic_rounds", "elastic_joins",
            "elastic_crashes", "elastic_tau_final",
            "elastic_full_barrier_stall_s", "elastic_quorum_stall_s",
            "elastic_stall_ratio")})
    # train-while-serve loop (subprocess; CPU path like the serving and
    # elastic legs) — promotions + zero-drop bar across generation swaps
    try:
        trainserve = bench_trainserve()
    except Exception as e:
        log(f"trainserve leg failed, omitting its fields: {e!r}")
    else:
        land("trainserve", {k: trainserve[k] for k in (
            "trainserve_promotions", "trainserve_rejections",
            "trainserve_staleness_mean", "trainserve_staleness_max",
            "trainserve_swap_p99_delta_ms", "trainserve_dropped",
            "trainserve_completed", "trainserve_generations",
            "trainserve_agreement_mean", "trainserve_traffic_records")})
    # serving degradation drill (subprocess; CPU path) — breaker trips,
    # recovery, sheds, exactly-once bar under seeded replica chaos
    try:
        resil = bench_serving_resilience()
    except Exception as e:
        log(f"serving_resilience leg failed, omitting its fields: {e!r}")
    else:
        land("serving_resilience", {k: resil[k] for k in (
            "serving_resilience_requests", "serving_resilience_completed",
            "serving_resilience_dropped", "serving_resilience_sheds",
            "serving_resilience_deadline_drops",
            "serving_resilience_breaker_trips",
            "serving_resilience_respawns",
            "serving_resilience_recovery_s",
            "serving_resilience_interactive_p99_ms",
            "serving_resilience_replay_bitwise")})
    # autoscaling drill (subprocess; CPU path) — the replica set grows
    # and shrinks through the placer, errstorm suppression, zero-drop
    # and bitwise-replay bars
    try:
        autoscale = bench_serving_autoscale()
    except Exception as e:
        log(f"serving_autoscale leg failed, omitting its fields: {e!r}")
    else:
        land("serving_autoscale", {k: autoscale[k] for k in (
            "serving_autoscale_pool", "serving_autoscale_ups",
            "serving_autoscale_downs", "serving_autoscale_min_active",
            "serving_autoscale_max_active", "serving_autoscale_dropped",
            "serving_autoscale_completed",
            "serving_autoscale_tail_p99_ms",
            "serving_autoscale_storm_trips",
            "serving_autoscale_storm_ups_during_outage",
            "serving_autoscale_replay_bitwise")})
    # fleet serving A/B (subprocess; CPU path) — OS-process workers vs
    # in-process replicas, interleaved bursts; zero-drop, zero-restart
    # and bitwise cross-process parity bars
    try:
        fleet = bench_serving_fleet()
    except Exception as e:
        log(f"serving_fleet leg failed, omitting its fields: {e!r}")
    else:
        land("serving_fleet", {k: fleet[k] for k in (
            "serving_fleet_workers", "serving_fleet_qps",
            "serving_fleet_single_qps", "serving_fleet_speedup",
            "serving_fleet_p50_ms", "serving_fleet_p99_ms",
            "serving_fleet_dropped", "serving_fleet_restarts",
            "serving_fleet_parity_failed")})
    # compound serving drill (subprocess; CPU path) — mixed windowed
    # detection + featurization + classify burst under seeded faults;
    # zero-partial, exactly-once, whole-request-shed and bitwise
    # served-vs-offline parity bars
    try:
        comp = bench_serving_compound()
    except Exception as e:
        log(f"serving_compound leg failed, omitting its fields: {e!r}")
    else:
        land("serving_compound", {k: comp[k] for k in (
            "serving_compound_requests", "serving_compound_completed",
            "serving_compound_dropped", "serving_compound_partials",
            "serving_compound_sheds",
            "serving_compound_sheds_interactive",
            "serving_compound_breaker_trips",
            "serving_compound_interactive_p99_ms",
            "serving_compound_ab_served_ms",
            "serving_compound_ab_offline_ms",
            "serving_compound_parity_failed",
            "serving_compound_replay_bitwise")})
    try:
        imgnet_native = bench_imagenet_native()
    except Exception as e:
        # one leg must degrade, not destroy, the record: every other
        # number above is already measured and persisted at this point
        log(f"imagenet_native leg failed, omitting its field: {e!r}")
    else:
        land("imagenet_native",
             {"imagenet_native_fed_imgs_per_sec":
              imgnet_native["imagenet_native_fed_imgs_per_sec"],
              "imagenet_native_batch":
              imgnet_native["imagenet_native_batch"],
              "imagenet_native_tau": imgnet_native["imagenet_native_tau"],
              "imagenet_native_precision":
              imgnet_native["imagenet_native_precision"],
              "imagenet_native_fused_blocks":
              imgnet_native["imagenet_native_fused_blocks"],
              "imagenet_native_ingest":
              imgnet_native["imagenet_native_ingest"],
              "imagenet_native_round_telemetry":
              imgnet_native["imagenet_native_round_telemetry"]})


if __name__ == "__main__":
    main()
