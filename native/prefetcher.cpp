// Native prefetching data loader: the TPU-host equivalent of the reference's
// C++ data tier — single reading thread per source deduped like DataReader
// (reference: caffe/src/caffe/data_reader.cpp:15-31), transform worker
// threads (reference: caffe/src/caffe/data_transformer.cpp — crop, mirror,
// mean subtract, scale), triple-buffered batch hand-off (reference:
// caffe/src/caffe/layers/base_data_layer.cpp:70-98, PREFETCH_COUNT=3), and
// context propagated at spawn (reference:
// caffe/src/caffe/internal_thread.cpp:21-50).
//
// Record format: fixed-size [1 label byte][C*H*W image bytes] — the CIFAR-10
// binary layout (reference: loaders/CifarLoader.scala:65-85), which the
// ArrayStore/db tools can also emit for arbitrary shapes.
//
// Exposed as a flat C API for ctypes binding (the libccaffe role,
// reference: libccaffe/ccaffe.h) — no Python objects cross the boundary,
// only raw pointers, exactly like the JNA bridge.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "blocking_queue.hpp"

namespace sparknet {

struct Record {
  int label;
  std::vector<uint8_t> pixels;  // C*H*W
};

struct Batch {
  std::vector<float> images;  // batch*C*crop*crop
  std::vector<int> labels;    // batch
};

struct LoaderConfig {
  int channels, height, width;
  int batch, crop;  // crop==0 -> no crop
  bool mirror, train;
  float scale;
  std::vector<float> mean;  // full-size C*H*W mean image, may be empty
  int num_threads, queue_depth;
  uint64_t seed;
};

class Loader {
 public:
  Loader(std::vector<std::string> files, LoaderConfig cfg)
      : files_(std::move(files)),
        cfg_(cfg),
        raw_queue_(static_cast<size_t>(cfg.queue_depth) * cfg.batch),
        full_queue_(static_cast<size_t>(cfg.queue_depth)) {
    reader_ = std::thread(&Loader::ReadLoop, this);
    for (int i = 0; i < cfg_.num_threads; ++i) {
      workers_.emplace_back(&Loader::TransformLoop, this, i);
    }
  }

  ~Loader() {
    stop_.store(true);
    raw_queue_.close();
    full_queue_.close();
    if (reader_.joinable()) reader_.join();
    for (auto& w : workers_) {
      if (w.joinable()) w.join();
    }
  }

  // Blocks until a batch is ready. Returns 0 on success, -1 if closed.
  int Next(float* out_images, int* out_labels) {
    Batch* b = nullptr;
    if (!full_queue_.pop(&b)) return -1;
    std::memcpy(out_images, b->images.data(),
                b->images.size() * sizeof(float));
    std::memcpy(out_labels, b->labels.data(), b->labels.size() * sizeof(int));
    delete b;
    return 0;
  }

 private:
  // One reading thread per source, like DataReader's deduped single-reader
  // bodies; loops over files forever (DB cursor wrap-around semantics).
  void ReadLoop() {
    const size_t rec_bytes =
        1 + static_cast<size_t>(cfg_.channels) * cfg_.height * cfg_.width;
    std::vector<uint8_t> buf(rec_bytes);
    while (!stop_.load()) {
      for (const auto& path : files_) {
        FILE* f = std::fopen(path.c_str(), "rb");
        if (!f) continue;
        while (!stop_.load() &&
               std::fread(buf.data(), 1, rec_bytes, f) == rec_bytes) {
          Record* r = new Record;
          r->label = buf[0];
          r->pixels.assign(buf.begin() + 1, buf.end());
          raw_queue_.push(r);
          if (stop_.load()) { delete r; break; }
        }
        std::fclose(f);
        if (stop_.load()) break;
      }
    }
  }

  // Transform workers: assemble batches; each worker owns its RNG seeded
  // from (seed, worker index) — the InternalThread context-propagation idea.
  void TransformLoop(int worker_id) {
    std::mt19937_64 rng(cfg_.seed + 0x9e3779b9u * (worker_id + 1));
    const int c = cfg_.channels, h = cfg_.height, w = cfg_.width;
    const int crop = cfg_.crop > 0 ? cfg_.crop : 0;
    const int oh = crop ? crop : h, ow = crop ? crop : w;
    while (!stop_.load()) {
      Batch* b = new Batch;
      b->images.resize(static_cast<size_t>(cfg_.batch) * c * oh * ow);
      b->labels.resize(cfg_.batch);
      bool ok = true;
      for (int i = 0; i < cfg_.batch; ++i) {
        Record* r = nullptr;
        if (!raw_queue_.pop(&r)) { ok = false; break; }
        b->labels[i] = r->label;
        int off_h = 0, off_w = 0;
        if (crop) {
          if (cfg_.train) {
            off_h = static_cast<int>(rng() % (h - crop + 1));
            off_w = static_cast<int>(rng() % (w - crop + 1));
          } else {  // center crop (data_transformer.cpp test phase)
            off_h = (h - crop) / 2;
            off_w = (w - crop) / 2;
          }
        }
        bool mirror = cfg_.mirror && cfg_.train && (rng() & 1);
        float* dst = b->images.data() +
                     static_cast<size_t>(i) * c * oh * ow;
        const uint8_t* src = r->pixels.data();
        const float* mean =
            cfg_.mean.empty() ? nullptr : cfg_.mean.data();
        for (int ch = 0; ch < c; ++ch) {
          for (int y = 0; y < oh; ++y) {
            for (int x = 0; x < ow; ++x) {
              int sy = y + off_h;
              int sx = mirror ? (w - 1 - (x + off_w)) : (x + off_w);
              size_t sidx =
                  (static_cast<size_t>(ch) * h + sy) * w + sx;
              float v = static_cast<float>(src[sidx]);
              if (mean) v -= mean[sidx];
              dst[(static_cast<size_t>(ch) * oh + y) * ow + x] =
                  v * cfg_.scale;
            }
          }
        }
        delete r;
      }
      if (!ok) { delete b; return; }
      full_queue_.push(b);
      if (stop_.load()) return;
    }
  }

  std::vector<std::string> files_;
  LoaderConfig cfg_;
  BlockingQueue<Record*> raw_queue_;
  BlockingQueue<Batch*> full_queue_;
  std::thread reader_;
  std::vector<std::thread> workers_;
  std::atomic<bool> stop_{false};
};

}  // namespace sparknet

extern "C" {

// Flat C API (the libccaffe pattern: opaque state pointer + plain types,
// reference: libccaffe/ccaffe.h:5-77).
void* snt_loader_create(const char** files, int nfiles, int channels,
                        int height, int width, int batch, int crop,
                        int mirror, int train, const float* mean,
                        float scale, int num_threads, int queue_depth,
                        uint64_t seed) {
  std::vector<std::string> fs(files, files + nfiles);
  sparknet::LoaderConfig cfg;
  cfg.channels = channels;
  cfg.height = height;
  cfg.width = width;
  cfg.batch = batch;
  cfg.crop = crop;
  cfg.mirror = mirror != 0;
  cfg.train = train != 0;
  cfg.scale = scale;
  if (mean) {
    cfg.mean.assign(mean,
                    mean + static_cast<size_t>(channels) * height * width);
  }
  cfg.num_threads = num_threads > 0 ? num_threads : 1;
  cfg.queue_depth = queue_depth > 0 ? queue_depth : 3;  // PREFETCH_COUNT
  cfg.seed = seed;
  return new sparknet::Loader(std::move(fs), cfg);
}

int snt_loader_next(void* handle, float* out_images, int* out_labels) {
  return static_cast<sparknet::Loader*>(handle)->Next(out_images, out_labels);
}

void snt_loader_destroy(void* handle) {
  delete static_cast<sparknet::Loader*>(handle);
}

}  // extern "C"
