// Bounded blocking queue — the native hand-off primitive of the data
// pipeline (reference: caffe/src/caffe/util/blocking_queue.cpp; used as a
// free/full buffer pair by BasePrefetchingDataLayer,
// caffe/src/caffe/layers/base_data_layer.cpp:70-98).
//
// std::mutex/condition_variable replace the reference's boost::thread
// machinery; semantics are identical (blocking push when bounded, blocking
// pop, peek-free).
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>

namespace sparknet {

template <typename T>
class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity = 0) : capacity_(capacity) {}

  void push(T value) {
    std::unique_lock<std::mutex> lock(mutex_);
    if (capacity_ > 0) {
      not_full_.wait(lock, [&] { return queue_.size() < capacity_ || closed_; });
    }
    if (closed_) return;
    queue_.push_back(std::move(value));
    not_empty_.notify_one();
  }

  // Blocking pop; returns false if the queue was closed and drained.
  bool pop(T* out) {
    std::unique_lock<std::mutex> lock(mutex_);
    not_empty_.wait(lock, [&] { return !queue_.empty() || closed_; });
    if (queue_.empty()) return false;
    *out = std::move(queue_.front());
    queue_.pop_front();
    not_full_.notify_one();
    return true;
  }

  bool try_pop(T* out) {
    std::lock_guard<std::mutex> lock(mutex_);
    if (queue_.empty()) return false;
    *out = std::move(queue_.front());
    queue_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void close() {
    std::lock_guard<std::mutex> lock(mutex_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  size_t size() const {
    std::lock_guard<std::mutex> lock(mutex_);
    return queue_.size();
  }

 private:
  mutable std::mutex mutex_;
  std::condition_variable not_empty_;
  std::condition_variable not_full_;
  std::deque<T> queue_;
  size_t capacity_;
  bool closed_ = false;
};

}  // namespace sparknet
