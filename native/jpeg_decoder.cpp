// Parallel JPEG decode + resize for the host data path.
//
// Role: the reference decodes JPEGs on the JVM with ImageIO/twelvemonkeys
// inside Spark executor parallelism (reference:
// src/main/scala/preprocessing/ScaleAndConvert.scala:16-27); a TPU-VM host
// has no executor fleet, so ImageNet-scale decode (256 imgs/step) needs
// native threads (SURVEY.md §7 "hard parts": input pipeline throughput).
// This library decodes a whole minibatch across a thread pool with libjpeg,
// DCT-prescales to the nearest power-of-two fraction >= target, finishes
// with bilinear resample, and emits planar RGB CHW uint8 — the ByteImage
// layout.  Corrupt images set ok[i]=0 and the caller drops them, matching
// ScaleAndConvert.scala:17-26.
//
// C API (ctypes-friendly, mirrors the libccaffe flat-function style,
// reference: libccaffe/ccaffe.h):
//   snt_jpeg_decode_batch(bufs, lens, n, th, tw, n_threads, out, ok)
//     out: n * 3 * th * tw uint8 (CHW per image); ok: n bytes 0/1.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <csetjmp>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void ErrorExit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// silent, but keep the warning counter (the default emit_message is what
// increments num_warnings; DecodeRGB treats any warning as corrupt)
void EmitNothing(j_common_ptr cinfo, int msg_level) {
  if (msg_level < 0) cinfo->err->num_warnings++;
}

// Decode one JPEG to interleaved RGB at the libjpeg-prescaled size.
// Returns false on corrupt input.
bool DecodeRGB(const uint8_t* buf, long len, int target_h, int target_w,
               std::vector<uint8_t>* rgb, int* out_h, int* out_w) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  // heap-owning locals live BEFORE the setjmp: a longjmp must not skip
  // their destructors (UB + leak per corrupt image otherwise)
  std::vector<uint8_t> row;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = ErrorExit;
  jerr.pub.emit_message = EmitNothing;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  // DCT prescale: pick denom in {1,2,4,8} keeping both dims >= target
  // (the bilinear finish then only ever downsamples by < 2x per axis)
  if (target_h > 0 && target_w > 0) {
    unsigned denom = 1;
    while (denom < 8 &&
           cinfo.image_height / (denom * 2) >= (unsigned)target_h &&
           cinfo.image_width / (denom * 2) >= (unsigned)target_w) {
      denom *= 2;
    }
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  const int h = cinfo.output_height;
  const int w = cinfo.output_width;
  // out_color_space=JCS_RGB makes libjpeg convert grayscale/YCbCr itself;
  // anything it can't convert (e.g. CMYK sources) is rejected
  if (cinfo.output_components != 3) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  row.resize(static_cast<size_t>(w) * 3);
  rgb->assign(static_cast<size_t>(h) * w * 3, 0);
  for (int y = 0; y < h; ++y) {
    uint8_t* rp = row.data();
    jpeg_read_scanlines(&cinfo, &rp, 1);
    std::memcpy(rgb->data() + static_cast<size_t>(y) * w * 3, row.data(),
                static_cast<size_t>(w) * 3);
  }
  jpeg_finish_decompress(&cinfo);
  // truncated/corrupt-but-recoverable streams only WARN (libjpeg fills
  // missing scanlines); count them as corrupt like the reference's decoder
  // failures (ScaleAndConvert.scala:17-26 drops on any decode exception)
  const bool clean = cinfo.err->num_warnings == 0;
  jpeg_destroy_decompress(&cinfo);
  *out_h = h;
  *out_w = w;
  return clean;
}

// Interleaved (h, w, 3) -> planar CHW (3, th, tw) with bilinear resample
// (align-corners=false, the Thumbnails.forceSize-style full-image map).
void ResizeToPlanar(const std::vector<uint8_t>& rgb, int h, int w, int th,
                    int tw, uint8_t* out) {
  const float sy = static_cast<float>(h) / th;
  const float sx = static_cast<float>(w) / tw;
  for (int y = 0; y < th; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    fy = std::max(0.0f, std::min(fy, static_cast<float>(h - 1)));
    const int y0 = static_cast<int>(fy);
    const int y1 = std::min(y0 + 1, h - 1);
    const float wy = fy - y0;
    for (int x = 0; x < tw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      fx = std::max(0.0f, std::min(fx, static_cast<float>(w - 1)));
      const int x0 = static_cast<int>(fx);
      const int x1 = std::min(x0 + 1, w - 1);
      const float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        const float v00 = rgb[(static_cast<size_t>(y0) * w + x0) * 3 + c];
        const float v01 = rgb[(static_cast<size_t>(y0) * w + x1) * 3 + c];
        const float v10 = rgb[(static_cast<size_t>(y1) * w + x0) * 3 + c];
        const float v11 = rgb[(static_cast<size_t>(y1) * w + x1) * 3 + c];
        const float v = (1 - wy) * ((1 - wx) * v00 + wx * v01) +
                        wy * ((1 - wx) * v10 + wx * v11);
        out[(static_cast<size_t>(c) * th + y) * tw + x] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Decode n JPEG buffers to (n, 3, th, tw) uint8 planar RGB using
// n_threads workers.  ok[i] = 1 on success, 0 for corrupt/unsupported.
void snt_jpeg_decode_batch(const uint8_t** bufs, const long* lens, int n,
                           int th, int tw, int n_threads, uint8_t* out,
                           uint8_t* ok) {
  std::atomic<int> next(0);
  const size_t img_size = static_cast<size_t>(3) * th * tw;
  auto worker = [&]() {
    std::vector<uint8_t> rgb;
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n) return;
      int h = 0, w = 0;
      if (DecodeRGB(bufs[i], lens[i], th, tw, &rgb, &h, &w)) {
        ResizeToPlanar(rgb, h, w, th, tw, out + img_size * i);
        ok[i] = 1;
      } else {
        std::memset(out + img_size * i, 0, img_size);
        ok[i] = 0;
      }
    }
  };
  const int nt = std::max(1, std::min(n_threads, n));
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
}

}  // extern "C"
